"""North-star benchmark: PromQL samples-scanned/sec on one chip.

Workload: the QueryInMemoryBenchmark-equivalent hot path (reference:
jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:45-249, scaled to
the BASELINE.json north-star config) — ``sum by (group)(rate(metric[5m]))``
over 1M series × 1h of samples, running the aligned-grid leaf kernel
(filodb_tpu/ops/grid.py): counter correction + windowed Prometheus rate +
grouped sum fused into one Pallas kernel.  This is the kernel the
device-resident serving path dispatches to when the layout invariant
holds; end-to-end served throughput is benchmarked separately in
benches/.

FOUR variants are measured and emitted (ISSUE 3; hist + topK ISSUE 14):

- ``dense``: the decoded-plane kernel (4 B/sample value plane, phase
  mode — no ts plane), the historical north-star number.
- ``compressed_resident``: the SAME query served from XOR-class packed
  residents (codecs/xorgrid.py, ~2.2 B/sample incl. meta), with the
  decode fused INSIDE the Pallas kernel (ops/grid.py
  rate_grid_grouped_packed) — the headline storage format measured on
  the headline path.  Equivalence against the ts-streaming kernel is
  asserted ON DEVICE before timing (like the phase-vs-ts check), and
  the workload's integer counters provably pack as one 16-bit class
  (residuals span <= bit 22 with >= 7 trailing zero bits), so group
  lanes stay contiguous.
- ``histogram_quantile``: BASELINE config 2 — ``histogram_quantile(
  0.99, sum(rate(latency_bucket[5m])) by (le))`` over packed HISTOGRAM
  bucket planes (xorgrid stride packs, ops/grid.py
  hist_quantile_grid_packed): VMEM decode + per-bucket rate + the
  banded-MXU bucket reduce + the le-interpolation in ONE program, only
  the [G, T] quantile plane read back.  Device equivalence vs the
  decoded-plane phase kernel + XLA bucket reduce + the shared
  hist_quantile math is asserted before timing.
- ``gdelt_topk``: BASELINE config 5 — the generic columnar
  scan->filter->topK program (ops/grid.py event_topk_grid_packed) over
  a two-column packed event table; equivalence vs the decoded-plane
  free kernel + XLA group reduce + top_k asserted before timing.
  Samples count BOTH scanned columns.
- ``mesh_fabric`` (ISSUE 18): the END-TO-END SPMD mesh query fabric —
  promql -> planner -> MeshReduceExec -> ONE shard_map program over
  N device-resident shards with the cross-shard psum on device.  Owns
  launches/query (must be exactly 1.0 warm, kernel-launch ledger at
  1-in-1 sampling) and achieved scan bytes/s; answers are asserted
  BIT-equal to the scatter-gather oracle before timing.
- ``query_batching`` (ISSUE 20): the fleet batching tier — K
  shape-identical concurrent queries rendezvoused by the QueryBatcher
  and executed as ONE vmapped device program.  Owns launches/query for
  a warm co-arrival fleet (must be <= ceil(K/max_batch)/K, kernel
  ledger at 1-in-1 sampling); every member's slice is asserted
  BIT-equal to its solo launch before timing.

The run FAILS (nonzero rc + machine-readable error JSON) if any
equivalence assertion trips or a measured variant regresses >20%
against the committed BASELINE.json floors — a bench regression
tripwire, not just a report.  A COMPILE/RUN failure of one of the two
NEW (ISSUE 14) variants is reported in its variants{} entry without
failing the legacy floors (their serving twin is breaker-guarded the
same way); a wrong ANSWER still fails loudly.

Protocol (see .claude/skills/verify/SKILL.md gotchas): data is generated
on-device from a scalar seed; the pipeline runs K statically-known
iterations, each forced by a ``float(...)`` readback; elapsed time subtracts
the measured 1-iteration variant so generation + RTT + readback cancel.
int32 timestamps / float32 values (TPU f64 is emulated).

Baseline: the reference publishes no absolute numbers and no JVM exists
in this environment (BASELINE.md), so ``vs_baseline`` is measured against
a multithreaded -O3 C++ implementation of the identical per-series /
per-window iterator workload (filodb_tpu/native/src/baseline.cpp — the
JVM-iterator-path proxy demanded by BASELINE.md's protocol), run on a
subsample and scaled per-sample.  Falls back to the single-core numpy
oracle below if no compiler is available.

Prints exactly ONE JSON line on stdout.
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def fail(msg: str, rc: int = 4):
    """Tripwire exit: ONE machine-readable JSON error line + nonzero rc
    (the driver treats any nonzero rc as a bench failure)."""
    log(f"BENCH TRIPWIRE: {msg}")
    print(json.dumps({
        "metric": "PromQL samples scanned/sec (rate()+sum-by)",
        "value": 0.0, "unit": "samples/sec", "vs_baseline": 0.0,
        "error": msg,
    }))
    sys.stdout.flush()
    sys.exit(rc)


G = int(os.environ.get("FILODB_BENCH_GROUPS", 1_000))   # sum by (group)
PER = int(os.environ.get("FILODB_BENCH_PER_GROUP", 1_000))
S = G * PER                                             # real series
NB = int(os.environ.get("FILODB_BENCH_ROWS", 60))       # 1h at 1m resolution
ITERS = int(os.environ.get("FILODB_BENCH_ITERS", 40))
WINDOW_MS = 300_000                                     # rate(...[5m])
STEP_MS = 60_000
SUB = int(os.environ.get("FILODB_BENCH_NUMPY_SERIES", 2_000))
CPP_SUB = int(os.environ.get("FILODB_BENCH_CPP_SERIES", 100_000))
GL = 1_024                                              # lanes per group
T0 = 600_000

# histogram_quantile variant (BASELINE config 2): G_H le-groups x P_H
# series x HB cumulative buckets = 1,048,576 stored bucket columns
HB = int(os.environ.get("FILODB_BENCH_HIST_BUCKETS", 16))
G_H = int(os.environ.get("FILODB_BENCH_HIST_GROUPS", 1_024))
P_H = int(os.environ.get("FILODB_BENCH_HIST_PER_GROUP", 64))
# GDELT topK variant (BASELINE config 5): event lanes, actor groups, k
E_L = int(os.environ.get("FILODB_BENCH_EVENT_LANES", 262_144))
E_G = int(os.environ.get("FILODB_BENCH_EVENT_GROUPS", 4_096))
E_K = int(os.environ.get("FILODB_BENCH_EVENT_K", 10))
# mesh fabric variant (ISSUE 18): the END-TO-END fused serving path —
# planner -> MeshReduceExec -> ONE shard_map program over N resident
# shards.  Small by design: it measures launches/query and per-query
# overhead of the real fabric, not raw kernel FLOPs (those are the four
# variants above).
M_SHARDS = int(os.environ.get("FILODB_BENCH_MESH_SHARDS", 8))
M_SERIES = int(os.environ.get("FILODB_BENCH_MESH_SERIES", 192))
M_ROWS = int(os.environ.get("FILODB_BENCH_MESH_ROWS", 240))
M_ITERS = int(os.environ.get("FILODB_BENCH_MESH_ITERS", 12))
# fleet batching variant (ISSUE 20): K shape-identical concurrent
# queries through the QueryBatcher — a warm co-arrival group must cost
# ceil(K/max_batch) vmapped launches, bit-equal to solo execution
QB_FLEET = int(os.environ.get("FILODB_BENCH_BATCH_FLEET", 8))
QB_SERIES = int(os.environ.get("FILODB_BENCH_BATCH_SERIES", 64))
QB_ROWS = int(os.environ.get("FILODB_BENCH_BATCH_ROWS", 120))
QB_ITERS = int(os.environ.get("FILODB_BENCH_BATCH_ITERS", 6))


def _probe_backend(timeout_s: int):
    """Initialize the JAX backend under a watchdog.

    During an axon-tunnel outage the TPU plugin *hangs* in init rather
    than raising (round-4 BENCH artifact was lost to this).  Init runs in
    a daemon thread; a hang or error becomes a fast, explicit exit with a
    machine-readable JSON error line instead of a driver-side timeout.
    Backend init is process-global, so the main thread reuses the
    initialized backend afterwards.
    """
    import threading

    box = {}

    def probe():
        try:
            import jax
            box["devices"] = [str(d) for d in jax.devices()]
        except Exception as e:  # noqa: BLE001 — report any init failure
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=probe, daemon=True, name="backend-probe")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return f"JAX backend init timed out after {timeout_s}s (TPU tunnel down?)"
    return box.get("error")


def main():
    err = _probe_backend(int(os.environ.get("FILODB_BENCH_PROBE_TIMEOUT_S", "180")))
    if err is not None:
        log(f"TPU unavailable: {err}")
        print(json.dumps({
            "metric": "PromQL samples scanned/sec (rate()+sum-by)",
            "value": 0.0, "unit": "samples/sec", "vs_baseline": 0.0,
            "error": f"TPU unavailable: {err}",
        }))
        sys.stdout.flush()
        os._exit(3)   # probe thread may still be wedged in native init

    import jax
    import jax.numpy as jnp

    from filodb_tpu.ops.grid import GridQuery, rate_grid_grouped

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")
    if jax.default_backend() not in ("tpu", "axon"):
        # hardware-absent CI: no throughput numbers are meaningful, but
        # BOTH variants still run end-to-end (tiny shapes, interpret
        # mode) so a broken kernel fails here, not only on the TPU
        _cpu_interpret_smoke()
        # the fabric + batching variants are backend-agnostic: run
        # their bit-equality and launch-count gates end-to-end even
        # without hardware
        _bench_mesh_fabric()
        _bench_query_batching()
        log("no TPU backend: interpret-mode variant smoke (all four "
            "kernel variants) + mesh-fabric + fleet-batching "
            "equivalence passed; skipping measurement")
        print(json.dumps({
            "metric": "PromQL samples scanned/sec (rate()+sum-by)",
            "value": 0.0, "unit": "samples/sec", "vs_baseline": 0.0,
            "error": "no TPU backend (interpret-mode equivalence smoke "
                     "of all four variants passed)",
        }))
        sys.stdout.flush()
        sys.exit(3)

    B = ((NB + 7) // 8) * 8                 # sublane-pad the bucket axis
    S_pad = G * GL
    steps_np = np.arange(T0 + WINDOW_MS, T0 + NB * STEP_MS, STEP_MS,
                        dtype=np.int32)
    T = len(steps_np)
    K = WINDOW_MS // STEP_MS
    # The generated workload satisfies the dense-lane contract (regular
    # scrapes: every live lane finite over all used rows, pad lanes
    # all-NaN) — verified on the device data below before timing.  This
    # is the same specialization the device store auto-detects from its
    # per-block fill ranges when serving real ingested data.
    q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, is_rate=True,
                  dense=True)

    def gen_body(seed):
        """On-device aligned-grid gen ([B, S] time-major): row c holds
        the sample with ts in (T0+(c-1)*step, T0+c*step].  Each series
        is scraped at a CONSTANT per-lane phase within its bucket —
        strictly more general than the reference benchmark data, whose
        producer emits exact-cadence timestamps identical across series
        (TestTimeseriesProducer.scala:128: ``startTime + n/numTs *
        10000``).  The store proves this uniform-phase layout per lane
        from block fill stats and serves it with the no-ts-plane phase
        kernels (memstore/devicestore.py); per-sample-jittered data
        falls back to the ts-streaming dense kernels."""
        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        base = (jnp.arange(B, dtype=jnp.int32) * STEP_MS
                + T0 - STEP_MS)[:, None]
        # headroom below STEP_MS: the timing loop bumps phase by +i per
        # iteration (see pipeline) and phase must stay in (0, gstep]
        phase = jax.random.randint(k1, (1, S_pad), 1,
                                   STEP_MS - ITERS - 1, jnp.int32)
        ts = base + phase
        incr = jax.random.uniform(k2, (B, S_pad), jnp.float32, 0.0, 10.0)
        vals = jnp.cumsum(incr, axis=0)
        lane = jnp.arange(S_pad, dtype=jnp.int32) % GL
        mask = ((jnp.arange(B) < NB)[:, None]) & ((lane < PER)[None, :])
        # kernel contract: row 0 = first bucket of the first window
        return ts[1:], jnp.where(mask, vals, jnp.nan)[1:], phase[0]

    def pipeline(ts, vals, phase, bump):
        # the serving path reads back (sum, count) partials and applies
        # the count>0 mask host-side during the aggregator merge — the
        # kernel's deliverable is the two [G, T] partials.  The CSE-
        # defeating bump perturbs the [1, S] phase row (4 MB), NOT the
        # [B, S] values plane: serving reads RESIDENT values, and a
        # per-iteration ``vals + bump`` would materialize a fresh 250 MB
        # array each query — traffic the server never pays.
        return rate_grid_grouped(None, vals, int(steps_np[0]), q,
                                 group_lanes=GL, phase=phase + bump)

    def build(iters: int):
        def f(seed):
            ts, vals, phase = gen_body(seed)
            acc = jnp.float32(0.0)
            for i in range(iters):
                s, c = pipeline(ts, vals, phase, jnp.int32(i))
                acc = acc + s[0, 0] + s[G // 2, T // 2] + c[0, 0]
            return acc
        return jax.jit(f)

    # prove the dense-lane contract on the rows the kernel uses
    def check_dense(seed):
        _, vals, _ = gen_body(seed)
        fin_cnt = jnp.isfinite(vals[:T + K - 1]).sum(axis=0)
        return jnp.all((fin_cnt == 0) | (fin_cnt == T + K - 1))
    if not bool(jax.jit(check_dense)(0)):
        fail("generated data violates the dense-lane contract")

    # the phase kernels must agree with the ts-streaming kernels on the
    # real device (CI exercises them in interpret mode only)
    def check_phase_equiv(seed):
        ts, vals, phase = gen_body(seed)
        s_ph, c_ph = rate_grid_grouped(None, vals, int(steps_np[0]), q,
                                       group_lanes=GL, phase=phase)
        s_ts, c_ts = rate_grid_grouped(ts, vals, int(steps_np[0]), q,
                                       group_lanes=GL)
        rel = jnp.abs(s_ph - s_ts) / jnp.maximum(jnp.abs(s_ts), 1e-6)
        return jnp.nanmax(jnp.where(c_ts > 0, rel, 0.0)), \
            jnp.max(jnp.abs(c_ph - c_ts))
    rel_err, cnt_err = jax.jit(check_phase_equiv)(0)
    rel_err, cnt_err = float(rel_err), float(cnt_err)
    log(f"phase-vs-ts kernel max rel err: {rel_err:.2e}; "
        f"count err: {cnt_err}")
    if not (rel_err < 2e-5 and cnt_err == 0):
        fail(f"phase kernel diverged from ts kernel "
             f"(rel={rel_err:.2e}, cnt={cnt_err})")

    f_base, f_full = build(1), build(1 + ITERS)
    log("compiling (1 and %d iteration variants)..." % (1 + ITERS))
    _ = float(f_base(0))
    _ = float(f_full(0))

    def timed(f, reps=7):
        best = []
        for _ in range(reps):
            a = time.perf_counter()
            _ = float(f(0))
            best.append(time.perf_counter() - a)
        return float(np.median(best))

    log("timing...")
    t_base = timed(f_base)
    t_full = timed(f_full)
    elapsed = max(t_full - t_base, 1e-9)
    # row 0 is clipped to meet the kernel row contract: NB-1 real buckets
    samples_per_query = S * (NB - 1)
    tpu_rate = samples_per_query * ITERS / elapsed
    log(f"device: {tpu_rate:.3e} samples/sec "
        f"({ITERS} queries in {elapsed:.3f}s; base {t_base:.3f}s, "
        f"full {t_full:.3f}s)")
    dense_bps = (B - 1) * 4 / (NB - 1) + 32 / (NB - 1)   # vals + phase8

    # ---- compressed-resident variant (ISSUE 3 tentpole) -------------------
    from filodb_tpu.codecs import xorgrid
    from filodb_tpu.ops.grid import rate_grid_grouped_packed

    rows_need = T + K - 1
    assert rows_need == NB - 1

    def gen_packed(seed):
        """Integer-counter workload whose XOR residuals provably fit ONE
        16-bit class: start = 2^23 + 128*r0 (r0 < 2^15) pins the f32
        exponent; increments 128*d (d in [1, 8)) give >= 7 trailing
        zero bits and bound block growth under 2^17, so residual bits
        span [7, 22] -> blen <= 16 for every lane.  Single class =
        identity lane order = group lanes stay contiguous for the
        fused grouped kernel.  Same mask/phase discipline as gen_body;
        only the used rows are packed (a NaN tail row would put a wide
        value->NaN residual in every live lane)."""
        key = jax.random.PRNGKey(seed + 7)
        k1, k2, k3 = jax.random.split(key, 3)
        phase = jax.random.randint(k1, (1, S_pad), 1, STEP_MS - 1,
                                   jnp.int32)
        start = (2.0 ** 23) + 128.0 * jax.random.randint(
            k2, (1, S_pad), 0, 2 ** 15, jnp.int32).astype(jnp.float32)
        incr = 128.0 * jax.random.randint(
            k3, (B, S_pad), 1, 8, jnp.int32).astype(jnp.float32)
        vals = start + jnp.cumsum(incr, axis=0)
        lane = jnp.arange(S_pad, dtype=jnp.int32) % GL
        mask = (lane < PER)[None, :]
        base = (jnp.arange(B, dtype=jnp.int32) * STEP_MS
                + T0 - STEP_MS)[:, None]
        ts = base + phase
        return (ts[1:1 + rows_need],
                jnp.where(mask, vals, jnp.nan)[1:1 + rows_need], phase[0])

    log("packing compressed-resident variant...")
    ts_pk, vals_pk, phase_pk = jax.jit(gen_packed)(0)
    vals_np = np.asarray(jax.device_get(vals_pk))
    packed = xorgrid.pack_vals(vals_np, phase=np.asarray(phase_pk),
                               min_width=16)
    if packed is None:
        fail("compressed-resident workload did not pack (class-16 "
             "guarantee violated?)")
    if not (packed.planes["p16"].shape[1] == S_pad
            and packed.planes["raw"].shape[1] == 0
            and bool((packed.inv == np.arange(S_pad)).all())):
        fail("compressed-resident pack is not a single identity-order "
             "class plane; group contiguity contract violated")
    # bit-exact CPU oracle check on a slice before trusting the device
    chk = xorgrid.unpack_vals(packed)[:, :4096]
    if not (chk.view(np.uint32) == vals_np[:, :4096].view(np.uint32)).all():
        fail("xorgrid CPU decode is not bit-identical to the packed "
             "input")
    planes_dev = {k: jax.device_put(jnp.asarray(v))
                  for k, v in packed.planes.items()}
    pk_read_bytes = sum(int(packed.planes[k].nbytes)
                        for k in ("p16", "m16"))
    pk_bps = pk_read_bytes / samples_per_query
    log(f"packed: {pk_read_bytes / 2**20:.1f} MiB resident "
        f"({pk_bps:.2f} B/sample vs {dense_bps:.2f} dense)")

    # in-bench DEVICE equivalence: the fused-decode kernel must agree
    # with the ts-streaming kernel on the same (decoded) data — the
    # compressed-resident analog of the phase-vs-ts check above
    def check_packed_equiv(planes):
        s_pk, c_pk = rate_grid_grouped_packed(planes, int(steps_np[0]), q,
                                              group_lanes=GL)
        s_ts, c_ts = rate_grid_grouped(ts_pk, vals_pk, int(steps_np[0]),
                                       q, group_lanes=GL)
        rel = jnp.abs(s_pk - s_ts) / jnp.maximum(jnp.abs(s_ts), 1e-6)
        return jnp.nanmax(jnp.where(c_ts > 0, rel, 0.0)), \
            jnp.max(jnp.abs(c_pk - c_ts))
    pk_rel, pk_cnt = jax.jit(check_packed_equiv)(planes_dev)
    pk_rel, pk_cnt = float(pk_rel), float(pk_cnt)
    log(f"packed-vs-ts kernel max rel err: {pk_rel:.2e}; "
        f"count err: {pk_cnt}")
    if not (pk_rel < 2e-5 and pk_cnt == 0):
        fail(f"compressed-resident kernel diverged from ts kernel "
             f"(rel={pk_rel:.2e}, cnt={pk_cnt})")

    def build_packed(iters: int):
        @jax.jit
        def f(planes):
            acc = jnp.float32(0.0)
            for i in range(iters):
                # distinct steps0 constants defeat CSE across the
                # unrolled queries; phase mode never reads it, exactly
                # like serving (resident meta is never perturbed)
                s, c = rate_grid_grouped_packed(
                    planes, int(steps_np[0]) + i, q, group_lanes=GL)
                acc = acc + s[0, 0] + s[G // 2, T // 2] + c[0, 0]
            return acc
        return f

    fp_base, fp_full = build_packed(1), build_packed(1 + ITERS)
    log("compiling packed variants...")
    _ = float(fp_base(planes_dev))
    _ = float(fp_full(planes_dev))
    log("timing packed...")
    tp_base = timed(lambda _s: fp_base(planes_dev))
    tp_full = timed(lambda _s: fp_full(planes_dev))
    pk_elapsed = max(tp_full - tp_base, 1e-9)
    pk_rate = samples_per_query * ITERS / pk_elapsed
    log(f"compressed-resident: {pk_rate:.3e} samples/sec "
        f"({ITERS} queries in {pk_elapsed:.3f}s)")

    # ---- histogram_quantile + GDELT-topK variants (ISSUE 14) --------------
    hist_var = _guarded_variant("histogram_quantile",
                                lambda: _bench_hist_quantile(timed))
    topk_var = _guarded_variant("gdelt_topk",
                                lambda: _bench_event_topk(timed))
    mesh_var = _guarded_variant("mesh_fabric", _bench_mesh_fabric)
    batch_var = _guarded_variant("query_batching", _bench_query_batching)

    # -- CPU baseline (C++ multithreaded JVM proxy) on a subsample ----------
    from filodb_tpu.native import baseline as cpp_baseline

    ts, vals, _phase = jax.jit(gen_body)(0)
    use_cpp = cpp_baseline.available()
    nsub = min(CPP_SUB if use_cpp else SUB, S)
    # real lanes (lane % GL < PER), walking whole groups first
    ngroups_needed = (nsub + PER - 1) // PER
    lanes = (np.arange(ngroups_needed)[:, None] * GL
             + np.arange(PER)[None, :]).ravel()[:nsub]
    lanes_j = jnp.asarray(lanes, dtype=jnp.int32)
    sub_ts = np.asarray(jax.device_get(ts[:, lanes_j])).astype(np.int64).T
    sub_vals = np.asarray(jax.device_get(vals[:, lanes_j])).astype(np.float64).T
    ids_np = np.zeros(nsub, dtype=np.int32)
    steps64 = steps_np.astype(np.int64)
    if use_cpp:
        nthreads = cpp_baseline.hw_threads()
        cpp_baseline.rate_sum(sub_ts[:64], sub_vals[:64], ids_np[:64], 1,
                              steps64, WINDOW_MS)       # warm (page-in)
        # best-of-3: this shared 1-core host swings >10x with co-tenant
        # load, and a slow baseline shot INFLATES vs_baseline — take the
        # least-contended run as the honest proxy of the machine
        np_elapsed = float("inf")
        for _ in range(3):
            a = time.perf_counter()
            cpp_out = cpp_baseline.rate_sum(sub_ts, sub_vals, ids_np, 1,
                                            steps64, WINDOW_MS)
            np_elapsed = min(np_elapsed, time.perf_counter() - a)
        np_rate = nsub * (NB - 1) / np_elapsed
        log(f"C++ baseline ({nthreads} threads): {np_rate:.3e} samples/sec "
            f"({nsub} series, best {np_elapsed:.3f}s of 3)")
        # cross-check vs the numpy oracle on a slice so the baseline can
        # never silently drift from the measured semantics
        ora = _numpy_rate_sum(sub_ts[:256], sub_vals[:256], ids_np[:256],
                              steps64)
        chk = cpp_baseline.rate_sum(sub_ts[:256], sub_vals[:256],
                                    ids_np[:256], 1, steps64, WINDOW_MS)
        assert np.allclose(ora, chk, rtol=1e-9, equal_nan=True), \
            "C++ baseline diverged from oracle"
    else:
        log(f"C++ baseline unavailable ({cpp_baseline.build_error()}); "
            "falling back to single-core numpy proxy")
        a = time.perf_counter()
        _numpy_rate_sum(sub_ts, sub_vals, ids_np, steps64)
        np_elapsed = time.perf_counter() - a
        np_rate = nsub * (NB - 1) / np_elapsed
        log(f"numpy proxy: {np_rate:.3e} samples/sec ({nsub} series, "
            f"{np_elapsed:.3f}s)")

    # ---- regression tripwire vs the committed BASELINE.json floors --------
    floors = {}
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as fh:
            floors = json.load(fh).get("floors", {})
    except Exception as e:  # noqa: BLE001 — a missing floor disables the wire
        log(f"no BASELINE.json floors ({e}); regression tripwire off")
    measured = [("dense", tpu_rate), ("compressed_resident", pk_rate)]
    for name, var in (("histogram_quantile", hist_var),
                      ("gdelt_topk", topk_var)):
        if "samples_per_sec" in var:
            measured.append((name, var["samples_per_sec"]))
    regressions = [
        f"{name} {rate:.3e} < 80% of committed floor {floors[name]:.3e}"
        for name, rate in measured
        if floors.get(name) and rate < 0.8 * float(floors[name])]
    if regressions:
        fail("bench regression: " + "; ".join(regressions), rc=5)

    print(json.dumps({
        "metric": "PromQL samples scanned/sec (rate()+sum-by, "
                  f"{S} series, 1h range)",
        "value": round(tpu_rate, 1),
        "unit": "samples/sec",
        "vs_baseline": round(tpu_rate / np_rate, 2),
        "variants": {
            "dense": {
                "samples_per_sec": round(tpu_rate, 1),
                "bytes_per_sample": round(dense_bps, 2),
                "equiv_max_rel_err": rel_err,
            },
            "compressed_resident": {
                "samples_per_sec": round(pk_rate, 1),
                "bytes_per_sample": round(pk_bps, 2),
                "equiv_max_rel_err": pk_rel,
            },
            "histogram_quantile": hist_var,
            "gdelt_topk": topk_var,
            "mesh_fabric": mesh_var,
            "query_batching": batch_var,
        },
    }))


def _guarded_variant(name: str, run):
    """Run one NEW (ISSUE 14) variant.  A wrong ANSWER inside `run`
    calls fail() and exits nonzero like every other assertion; a
    COMPILE/RUN crash (a backend whose Mosaic build rejects the new
    kernels) is reported in the variant entry instead of sinking the
    legacy floors — the serving twin of these kernels is breaker-
    guarded the same way (memstore/devicestore.py _run_packed)."""
    try:
        return run()
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — see docstring
        log(f"{name} variant failed to build/run: {e!r}")
        return {"error": f"{type(e).__name__}: {e}"}


def _c16_jax(key, rows: int, cols: int):
    """On-device integer-counter plane with the 16-bit-class guarantee
    (gen_packed's construction: pinned f32 exponent, >=7 trailing zero
    bits — ONE definition shared by every variant so the pack contract
    the bench measures can never drift between them)."""
    import jax
    import jax.numpy as jnp

    ka, kb = jax.random.split(key)
    start = (2.0 ** 23) + 128.0 * jax.random.randint(
        ka, (1, cols), 0, 2 ** 15, jnp.int32).astype(jnp.float32)
    incr = 128.0 * jax.random.randint(
        kb, (rows, cols), 1, 8, jnp.int32).astype(jnp.float32)
    return start + jnp.cumsum(incr, axis=0)


def _c16_np(rng, rows: int, cols: int):
    """Numpy twin of :func:`_c16_jax` for the interpret smoke."""
    start = (2 ** 23 + 128 * rng.integers(0, 2 ** 15, cols)) \
        .astype(np.float32)
    inc = 128 * rng.integers(1, 8, (rows, cols))
    return (start[None, :] + np.cumsum(inc, axis=0)).astype(np.float32)


def _hist_phase_series(rng_key, cols: int, hb: int, rows: int):
    """Hist bucket-plane gen: one :func:`_c16_jax` counter per bucket
    column, one constant scrape phase per SERIES (shared by its hb
    columns)."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(rng_key)
    nser = cols // hb
    phase = jnp.repeat(
        jax.random.randint(k1, (nser,), 1, STEP_MS - 1, jnp.int32), hb)
    return _c16_jax(k2, rows, cols), phase


def _bench_hist_quantile(timed):
    """histogram_quantile(0.99, sum(rate(bucket[5m])) by (le-group))
    over packed hist residents — fused decode + banded bucket reduce +
    le-interpolation (ops/grid.py hist_quantile_grid_packed)."""
    import jax
    import jax.numpy as jnp

    from filodb_tpu.codecs import xorgrid
    from filodb_tpu.ops import histogram_ops
    from filodb_tpu.ops.grid import (GridQuery, hist_quantile_grid_packed,
                                     rate_grid)

    cols = G_H * P_H * HB
    group_lanes = P_H * HB
    K = WINDOW_MS // STEP_MS
    steps_np = np.arange(T0 + WINDOW_MS, T0 + NB * STEP_MS, STEP_MS,
                         dtype=np.int32)
    T = len(steps_np)
    rows_need = T + K - 1
    q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, is_rate=True,
                  dense=True)
    tops = np.concatenate([2.0 ** np.arange(HB - 1), [np.inf]])
    log(f"hist variant: packing {cols} bucket columns "
        f"({G_H} groups x {P_H} series x {HB} buckets)...")
    vals, phase = jax.jit(lambda s: _hist_phase_series(
        jax.random.PRNGKey(s + 11), cols, HB, rows_need))(0)
    vals_np = np.asarray(jax.device_get(vals))
    packed = xorgrid.pack_vals(vals_np, phase=np.asarray(phase),
                               min_width=16, stride=HB)
    if packed is None or not (
            packed.planes["p16"].shape[1] == cols
            and bool((packed.inv == np.arange(cols)).all())):
        fail("hist workload did not pack as one identity-order class "
             "plane (stride contract violated?)")
    chk = xorgrid.unpack_vals(packed)[:, :4096]
    if not (chk.view(np.uint32) == vals_np[:, :4096].view(np.uint32)).all():
        fail("xorgrid hist CPU decode not bit-identical")
    planes_dev = {k: jax.device_put(jnp.asarray(v))
                  for k, v in packed.planes.items()}
    bps = sum(int(packed.planes[k].nbytes) for k in ("p16", "m16")) \
        / (cols * (NB - 1))

    # device equivalence: fused hist program vs decoded-plane phase
    # kernel + XLA bucket reduce + the SAME hist_quantile math.  The
    # NaN pattern is compared EXPLICITLY — a bare nanmax would let a
    # liveness bug (wrong group NaN on one side) pass silently
    def check(planes):
        fused = hist_quantile_grid_packed(planes, int(steps_np[0]),
                                          jnp.asarray(tops), q, 0.99, HB,
                                          group_lanes=group_lanes)
        stepped = rate_grid(None, vals, int(steps_np[0]), q, lanes=1024,
                            phase=phase)                 # [T, cols]
        st = stepped.reshape(T, G_H, P_H, HB)
        hist_sum = jnp.nansum(st, axis=2).transpose(1, 0, 2)  # [G,T,HB]
        ref = histogram_ops.hist_quantile(jnp.asarray(tops), hist_sum,
                                          0.99)
        ff, fr = jnp.isfinite(fused), jnp.isfinite(ref)
        mism = jnp.sum(ff != fr)
        rel = jnp.where(ff & fr,
                        jnp.abs(fused - ref)
                        / jnp.maximum(jnp.abs(ref), 1e-6), 0.0)
        return jnp.max(rel), mism
    h_rel, h_mism = jax.jit(check)(planes_dev)
    h_rel, h_mism = float(h_rel), int(h_mism)
    log(f"hist fused-vs-XLA max rel err: {h_rel:.2e}; "
        f"NaN-pattern mismatches: {h_mism}")
    if not (h_rel < 2e-5 and h_mism == 0):
        fail(f"fused hist quantile diverged from the XLA decode path "
             f"(rel={h_rel:.2e}, nan_mismatch={h_mism})")

    def build(iters: int):
        @jax.jit
        def f(planes):
            acc = jnp.float32(0.0)
            for i in range(iters):
                out = hist_quantile_grid_packed(
                    planes, int(steps_np[0]) + i, jnp.asarray(tops), q,
                    0.99, HB, group_lanes=group_lanes)
                acc = acc + out[0, 0] + out[G_H // 2, T // 2]
            return acc
        return f
    fb, ff = build(1), build(1 + ITERS)
    log("compiling hist variants...")
    _ = float(fb(planes_dev))
    _ = float(ff(planes_dev))
    log("timing hist...")
    el = max(timed(lambda _s: ff(planes_dev))
             - timed(lambda _s: fb(planes_dev)), 1e-9)
    samples = cols * (NB - 1)
    rate = samples * ITERS / el
    log(f"histogram_quantile: {rate:.3e} samples/sec "
        f"({ITERS} queries in {el:.3f}s)")
    return {"samples_per_sec": round(rate, 1),
            "bytes_per_sample": round(bps, 2),
            "equiv_max_rel_err": h_rel}


def _bench_event_topk(timed):
    """topk(k, sum_over_time(value[w]) by (actor)) with a last-value
    filter on a second column — the generic columnar scan-filter-topK
    program (ops/grid.py event_topk_grid_packed)."""
    import jax
    import jax.numpy as jnp

    from filodb_tpu.codecs import xorgrid
    from filodb_tpu.ops.grid import (GridQuery, event_topk_grid_packed,
                                     rate_grid)

    K = WINDOW_MS // STEP_MS
    steps_np = np.arange(T0 + WINDOW_MS, T0 + NB * STEP_MS, STEP_MS,
                         dtype=np.int32)
    T = len(steps_np)
    rows_need = T + K - 1
    qs = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, op="sum",
                   is_rate=False, dense=True)
    ql = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, op="last",
                   is_rate=False, dense=True)
    log(f"event variant: packing 2 columns x {E_L} lanes "
        f"({E_G} groups, k={E_K})...")

    def gen(seed):
        key = jax.random.PRNGKey(seed + 23)
        k1, k2 = jax.random.split(key)
        return (_c16_jax(k1, rows_need, E_L),
                _c16_jax(k2, rows_need, E_L))
    vals, fvals = jax.jit(gen)(0)
    vals_np = np.asarray(jax.device_get(vals))
    fvals_np = np.asarray(jax.device_get(fvals))
    pk_v = xorgrid.pack_vals(vals_np, min_width=16)
    pk_f = xorgrid.pack_vals(fvals_np, min_width=16)
    if pk_v is None or pk_f is None \
            or not (pk_v.inv == np.arange(E_L)).all() \
            or not (pk_f.inv == np.arange(E_L)).all():
        fail("event workload did not pack as identity-order class planes")
    dev_v = {k: jax.device_put(jnp.asarray(v))
             for k, v in pk_v.planes.items()}
    dev_f = {k: jax.device_put(jnp.asarray(v))
             for k, v in pk_f.planes.items()}
    # actor groups are contiguous lane runs: the banded group_width
    # form reduces with a reshape-sum — no [lanes, G] one-hot operand
    per = E_L // E_G
    thresh = float(np.median(fvals_np[-1]))
    bps = (sum(int(pk_v.planes[k].nbytes) for k in ("p16", "m16"))
           + sum(int(pk_f.planes[k].nbytes) for k in ("p16", "m16"))) \
        / (2 * E_L * (NB - 1))

    # NaN pattern compared explicitly, like the hist gate above
    def check(dv, df):
        f_vals, f_idx = event_topk_grid_packed(
            dv, int(steps_np[0]), qs, E_K, None, E_G,
            filt_packed=df, filt_op="gt", filt_thresh=thresh,
            filt_q=ql, group_width=per)
        sv = rate_grid(None, vals, int(steps_np[0]), qs, lanes=1024)
        sf = rate_grid(None, fvals, int(steps_np[0]), ql, lanes=1024)
        masked = jnp.where(sf > thresh, sv, jnp.nan)
        fin = jnp.isfinite(masked)
        gs = jnp.where(fin, masked, 0.0).reshape(T, E_G, per).sum(2)
        gc = fin.reshape(T, E_G, per).sum(2)
        ranked = jnp.where(gc > 0, gs, -jnp.inf)
        r_vals, _r_idx = jax.lax.top_k(ranked, E_K)
        r_vals = jnp.where(jnp.isfinite(r_vals), r_vals, jnp.nan)
        ff_, fr_ = jnp.isfinite(f_vals), jnp.isfinite(r_vals)
        mism = jnp.sum(ff_ != fr_)
        rel = jnp.where(ff_ & fr_,
                        jnp.abs(f_vals - r_vals)
                        / jnp.maximum(jnp.abs(r_vals), 1e-6), 0.0)
        return jnp.max(rel), mism
    t_rel, t_mism = jax.jit(check)(dev_v, dev_f)
    t_rel, t_mism = float(t_rel), int(t_mism)
    log(f"event topk fused-vs-XLA max rel err: {t_rel:.2e}; "
        f"NaN-pattern mismatches: {t_mism}")
    if not (t_rel < 2e-5 and t_mism == 0):
        fail(f"fused event topK diverged from the XLA decode path "
             f"(rel={t_rel:.2e}, nan_mismatch={t_mism})")

    def build(iters: int):
        @jax.jit
        def f(dv, df):
            acc = jnp.float32(0.0)
            for i in range(iters):
                tv, ti = event_topk_grid_packed(
                    dv, int(steps_np[0]) + i, qs, E_K, None, E_G,
                    filt_packed=df, filt_op="gt", filt_thresh=thresh,
                    filt_q=ql, group_width=per)
                acc = acc + tv[0, 0] + ti[T // 2, 0].astype(jnp.float32)
            return acc
        return f
    fb, ff = build(1), build(1 + ITERS)
    log("compiling event variants...")
    _ = float(fb(dev_v, dev_f))
    _ = float(ff(dev_v, dev_f))
    log("timing event topk...")
    el = max(timed(lambda _s: ff(dev_v, dev_f))
             - timed(lambda _s: fb(dev_v, dev_f)), 1e-9)
    samples = 2 * E_L * (NB - 1)          # both scanned columns count
    rate = samples * ITERS / el
    log(f"gdelt_topk: {rate:.3e} samples/sec "
        f"({ITERS} queries in {el:.3f}s)")
    return {"samples_per_sec": round(rate, 1),
            "bytes_per_sample": round(bps, 2),
            "equiv_max_rel_err": t_rel}


def _bench_mesh_fabric():
    """SPMD mesh query fabric (ISSUE 18): ``sum by (grp)(metric)`` over
    M_SHARDS device-resident shards served END-TO-END — promql parse ->
    planner -> MeshReduceExec -> ONE compiled shard_map program with the
    cross-shard psum on device and a single [G, T] readback.  Unlike the
    kernel variants above this runs the real serving stack, so the
    numbers it owns are launches/query (from the kernel-launch ledger at
    1-in-1 sampling — MUST be exactly 1.0 warm) and achieved scan
    bytes/s.  Device equivalence vs the scatter-gather oracle is
    asserted BIT-exact before timing: the workload is dyadic (integer
    multiples of 1/8, group sums < 2^24 eighths) so every sum is exact
    in BOTH f32 (TPU grid planes) and f64 (host oracle) at any
    summation order."""
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel import meshgrid
    from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
    from filodb_tpu.parallel.shardmap import ShardMapper, shard_of_tags
    from filodb_tpu.promql.parser import query_range_to_logical_plan
    from filodb_tpu.query.exec import ExecContext
    from filodb_tpu.query.model import QueryContext
    from filodb_tpu.utils.devicewatch import KERNEL_TIMER, device_metrics

    base, gstep = 1_700_000_000_000, 10_000
    spread = max(M_SHARDS.bit_length() - 1, 0)
    start = base + 300_000                  # 5m lookback stays in-span
    end = base + (M_ROWS - 1) * gstep
    log(f"mesh fabric: {M_SERIES} series over {M_SHARDS} shards x "
        f"{M_ROWS} rows...")
    ms = TimeSeriesMemStore()
    opts = DatasetOptions()
    mapper = ShardMapper(M_SHARDS)
    for s in range(M_SHARDS):
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(101)
    for i in range(M_SERIES):
        tags = {"_metric_": "mf", "inst": f"i{i}", "grp": f"g{i % 16}",
                "_ws_": "w", "_ns_": "n"}
        shard = shard_of_tags(tags, M_SHARDS, spread, opts)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                          container_size=1 << 20)
        ts = base + np.arange(M_ROWS) * gstep
        dyadic = rng.integers(1, 1 << 15, M_ROWS).astype(np.float64) / 8.0
        b.add_series(ts.tolist(), [dyadic.tolist()], tags)
        for off, c in enumerate(b.containers()):
            ms.get_shard("prom", shard).ingest_container(c, off)

    def planner(mesh: bool):
        provider = None
        if mesh:
            engine = MeshEngine(make_mesh())
            provider = lambda: engine  # noqa: E731
        return SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                    spread_default=spread,
                                    mesh_engine_provider=provider)

    lp = query_range_to_logical_plan(
        'sum by (grp)(mf{_ws_="w",_ns_="n"})', start, 30_000, end)

    def run(pl):
        res = pl.materialize(lp, QueryContext()) \
            .execute(ExecContext(ms, QueryContext()))
        out = {}
        for bt in res.batches:
            for tg, tss, vs in bt.to_series():
                out[tuple(sorted(tg.items()))] = (np.asarray(tss),
                                                  np.asarray(vs))
        return out

    fused_pl, oracle_pl = planner(True), planner(False)
    got, want = run(fused_pl), run(oracle_pl)
    if set(got) != set(want) or not want:
        fail("mesh fabric answered a different series set than the "
             "scatter-gather oracle")
    for k in want:
        ga = np.asarray(got[k][1], dtype=np.float64)
        wa = np.asarray(want[k][1], dtype=np.float64)
        if not (np.array_equal(np.isnan(ga), np.isnan(wa))
                and ga.tobytes() == wa.tobytes()):
            fail(f"mesh fabric NOT bit-equal to scatter-gather for {k}")
    serves0 = meshgrid.STATS["fused_serves"]
    prev = KERNEL_TIMER.sample_1_in
    KERNEL_TIMER.configure(sample_1_in=1)
    try:
        run(fused_pl)                       # warm under 1-in-1 sampling
        c = device_metrics()["kernel_launches"]
        before = c.total()
        a = time.perf_counter()
        for _ in range(M_ITERS):
            run(fused_pl)
        el = max(time.perf_counter() - a, 1e-9)
        launches = (c.total() - before) / M_ITERS
    finally:
        KERNEL_TIMER.configure(sample_1_in=prev)
    if meshgrid.STATS["fused_serves"] <= serves0:
        fail("mesh fabric never took the fused rung (fallback served "
             "the bench workload)")
    if launches != 1.0:
        fail(f"warm mesh-fabric query is not ONE compiled launch "
             f"(measured {launches:.2f}/query)")
    # every step scans its 5m lookback window from the f32 grid plane
    nsteps = (end - start) // 30_000 + 1
    samples = M_SERIES * nsteps * (300_000 // gstep)
    rate = samples * M_ITERS / el
    log(f"mesh_fabric: {launches:.1f} launch/query, {rate:.3e} "
        f"samples/sec ({M_ITERS} queries in {el:.3f}s)")
    return {"launches_per_query": launches,
            "samples_per_sec": round(rate, 1),
            "bytes_per_sec": round(rate * 4, 1),   # f32 resident plane
            "equiv": "bitwise"}


def _bench_query_batching():
    """Fleet batching tier (ISSUE 20): QB_FLEET shape-identical
    concurrent ``rate()`` range queries (same resident planes, same
    grid shape, starts shifted by i*step) dispatched through the
    ``QueryBatcher`` from barrier-released threads.  A warm co-arrival
    fleet must cost ceil(K/max_batch) vmapped device launches — ONE
    stacked program + ONE readback for the whole group, counted by the
    kernel-launch ledger at 1-in-1 sampling — and every member's slice
    is asserted BIT-equal to its solo (batcher-less) launch before
    anything is timed.  Backend-agnostic: the gates run on CPU CI too."""
    import threading

    from filodb_tpu.batching import QueryBatcher, reset_batch_breaker
    from filodb_tpu.core.filters import ColumnFilter, Equals
    from filodb_tpu.core.record import RecordBuilder, decode_container
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.query.logical import RangeFunctionId as F
    from filodb_tpu.utils.devicewatch import KERNEL_TIMER, device_metrics

    base, step, window = 1_700_000_040_000, 60_000, 300_000
    kbuckets = window // step
    fleet = QB_FLEET
    log(f"query batching: fleet of {fleet} over {QB_SERIES} series x "
        f"{QB_ROWS} rows...")
    ms = TimeSeriesMemStore()
    shard = ms.setup("prom", DEFAULT_SCHEMAS, 0)
    rng = np.random.default_rng(7)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(QB_SERIES):
        tags = {"__name__": "fleet_total", "instance": f"i{i}",
                "_ws_": "w", "_ns_": "n"}
        ts = (base + np.arange(QB_ROWS, dtype=np.int64) * step - step + 1
              + rng.integers(0, 30_000, size=QB_ROWS))
        vals = np.cumsum(rng.random(QB_ROWS) * 5)
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
    shard.flush_all()
    pids = shard.lookup_partitions(
        [ColumnFilter("_metric_", Equals("fleet_total"))], 0,
        2**62).part_ids
    steps0 = base + (kbuckets - 1) * step
    nsteps = QB_ROWS - kbuckets - fleet - 1
    starts = [steps0 + i * step for i in range(fleet)]

    # solo oracle: the per-query chain with no batcher attached
    solos = []
    for s0 in starts:
        got = shard.scan_grid(pids, F.RATE, s0, nsteps, step, window)
        if got is None:
            fail("fleet-batching bench workload declined the grid path")
        solos.append(np.asarray(got[1]))

    reset_batch_breaker()
    bat = QueryBatcher(enabled=True, window_ms=1_000.0, max_batch=fleet,
                       hot_ttl_s=60.0, dataset="prom")
    shard.query_batcher = bat

    def fleet_round():
        barrier = threading.Barrier(fleet)
        outs = [None] * fleet

        def worker(i, s0):
            barrier.wait()
            got = shard.scan_grid(pids, F.RATE, s0, nsteps, step,
                                  window)
            outs[i] = None if got is None else np.asarray(got[1])

        ths = [threading.Thread(target=worker, args=(i, s0))
               for i, s0 in enumerate(starts)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        return outs

    try:
        # bootstrap: a cold key only groups off a detected overlap, so
        # round until the key is hot (also warms the padded-B compile)
        for _ in range(10):
            fleet_round()
            if bat.snapshot()["realized_peak"] >= 2:
                break
        if bat.snapshot()["realized_peak"] < 2:
            fail("fleet-batching bench never formed a co-arrival group")
        fleet_round()        # one hot round: warm the full-B compile
        prev = KERNEL_TIMER.sample_1_in
        KERNEL_TIMER.configure(sample_1_in=1)
        try:
            c = device_metrics()["kernel_launches"]
            before = c.total()
            a = time.perf_counter()
            rounds = []
            for _ in range(QB_ITERS):
                rounds.append(fleet_round())
            el = max(time.perf_counter() - a, 1e-9)
            launches = (c.total() - before) / (QB_ITERS * fleet)
        finally:
            KERNEL_TIMER.configure(sample_1_in=prev)
        for outs in rounds:
            for i, out in enumerate(outs):
                if out is None or out.tobytes() != solos[i].tobytes():
                    fail(f"fleet-batching member {i} is NOT bit-equal "
                         f"to its solo launch")
    finally:
        shard.query_batcher = None
    budget = -(-fleet // bat.max_batch) / fleet       # ceil(K/max)/K
    if launches > budget:
        fail(f"warm fleet of {fleet} cost {launches:.3f} launches/query "
             f"(> {budget:.3f} = ceil(K/max_batch)/K): the co-arrival "
             f"group is not ONE stacked launch")
    samples = QB_SERIES * nsteps * kbuckets
    rate = samples * QB_ITERS * fleet / el
    realized = bat.snapshot()["realized_peak"]
    log(f"query_batching: {launches:.3f} launches/query (fleet={fleet}, "
        f"peak group={realized}), {rate:.3e} samples/sec")
    return {"launches_per_query": round(launches, 4),
            "fleet": fleet, "peak_group": realized,
            "samples_per_sec": round(rate, 1),
            "equiv": "bitwise"}


def _cpu_interpret_smoke():
    """Tiny end-to-end run of EVERY north-star variant in Pallas
    interpret mode (the hardware-absent CI clause): dense phase kernel
    vs the fused compressed-resident kernel on identical data, grouped
    partials must agree; the hist-quantile and event-topK programs run
    against their XLA decode oracles the same way."""
    import jax
    import jax.numpy as jnp

    from filodb_tpu.codecs import xorgrid
    from filodb_tpu.ops.grid import (GridQuery, rate_grid_grouped,
                                     rate_grid_grouped_packed)

    rng = np.random.default_rng(0)
    rows, gl, groups = 64, 128, 8      # rows >= 64: meta amortized past
    #                                    the packer's >=25% threshold
    L = gl * groups
    start = (2 ** 23 + 128 * rng.integers(0, 2 ** 15, L)).astype(np.float32)
    inc = 128 * rng.integers(1, 8, (rows, L))
    vals = (start[None, :] + np.cumsum(inc, axis=0)).astype(np.float32)
    phase = rng.integers(1, STEP_MS, L).astype(np.int32)
    packed = xorgrid.pack_vals(vals, phase=phase, min_width=16)
    assert packed is not None and (packed.inv == np.arange(L)).all(), \
        "smoke workload failed the single-class pack contract"
    planes = {k: jnp.asarray(v) for k, v in packed.planes.items()}
    T, K = 20, 5
    q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, is_rate=True,
                  dense=True)
    s_d, c_d = rate_grid_grouped(None, jnp.asarray(vals[:T + K - 1]), 0,
                                 q, group_lanes=gl, interpret=True,
                                 phase=phase)
    s_p, c_p = rate_grid_grouped_packed(planes, 0, q, group_lanes=gl,
                                        interpret=True)
    rel = float(np.nanmax(np.abs(np.asarray(s_p) - np.asarray(s_d))
                          / np.maximum(np.abs(np.asarray(s_d)), 1e-6)))
    cnt = float(np.max(np.abs(np.asarray(c_p) - np.asarray(c_d))))
    log(f"interpret smoke: dense-vs-compressed rel={rel:.2e} cnt={cnt}")
    if not (rel < 1e-5 and cnt == 0):
        fail(f"interpret-mode variant smoke diverged (rel={rel:.2e}, "
             f"cnt={cnt})")
    _hist_topk_interpret_smoke(rng, T, K, q)


def _hist_topk_interpret_smoke(rng, T, K, q):
    """Interpret-mode twins of the hist-quantile and event-topK
    variants: fused programs vs their XLA decode oracles on tiny
    shapes, so a broken new kernel fails in CPU CI, not only on TPU."""
    import jax.numpy as jnp

    from filodb_tpu.codecs import xorgrid
    from filodb_tpu.ops import histogram_ops
    from filodb_tpu.ops.grid import (GridQuery, event_topk_grid_packed,
                                     hist_quantile_grid_packed,
                                     rate_grid_ref)

    rows = 64          # >= T+K-1; 64 amortizes the meta tiles past the
    #                    packer's >=25% threshold (the kernel decodes
    #                    the whole block and slices the query rows)
    used = T + K - 1
    # hist: 4 groups x 8 series x 4 buckets
    hb, per, gh = 4, 8, 4
    cols = gh * per * hb
    hv = _c16_np(rng, rows, cols)
    phase = np.repeat(rng.integers(1, STEP_MS, cols // hb), hb) \
        .astype(np.int32)
    pk = xorgrid.pack_vals(hv, phase=phase, min_width=16, stride=hb)
    assert pk is not None and (pk.inv == np.arange(cols)).all(), \
        "hist smoke failed the stride pack contract"
    planes = {k: jnp.asarray(v) for k, v in pk.planes.items()}
    tops = np.concatenate([2.0 ** np.arange(hb - 1), [np.inf]])
    fused = np.asarray(hist_quantile_grid_packed(
        planes, 0, jnp.asarray(tops), q, 0.9, hb, group_lanes=per * hb,
        interpret=True))
    stepped = np.asarray(rate_grid_ref(None, jnp.asarray(hv[:used]), 0,
                                       q, phase=phase))
    hs = stepped.reshape(T, gh, per, hb).sum(2).transpose(1, 0, 2)
    ref = np.asarray(histogram_ops.hist_quantile(
        jnp.asarray(tops), jnp.asarray(hs), 0.9))
    h_rel = float(np.nanmax(np.abs(fused - ref)
                            / np.maximum(np.abs(ref), 1e-6)))
    log(f"interpret smoke: hist fused-vs-XLA rel={h_rel:.2e}")
    if not h_rel < 1e-5:
        fail(f"interpret-mode hist quantile smoke diverged "
             f"(rel={h_rel:.2e})")
    # event topK: 256 lanes, 8 contiguous groups (the banded
    # group_width form the TPU variant measures), filter column, k=3
    el, eg, k = 256, 8, 3
    v = _c16_np(rng, rows, el)
    fv = _c16_np(rng, rows, el)
    pv, pf = (xorgrid.pack_vals(x, min_width=16) for x in (v, fv))
    dv = {kk: jnp.asarray(a) for kk, a in pv.planes.items()}
    df = {kk: jnp.asarray(a) for kk, a in pf.planes.items()}
    qs = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, op="sum",
                   is_rate=False, dense=True)
    ql = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, op="last",
                   is_rate=False, dense=True)
    thr = float(np.median(fv[used - 1]))   # ~half the lanes pass
    tv, _ti = event_topk_grid_packed(
        dv, 0, qs, k, None, eg, filt_packed=df,
        filt_op="gt", filt_thresh=thr, filt_q=ql, interpret=True,
        group_width=el // eg)
    sv = np.asarray(rate_grid_ref(None, jnp.asarray(v[:used]), 0, qs))
    sf = np.asarray(rate_grid_ref(None, jnp.asarray(fv[:used]), 0, ql))
    masked = np.where(sf > thr, sv, np.nan)
    fin = np.isfinite(masked)
    gs = np.where(fin, masked, 0.0).reshape(T, eg, el // eg).sum(2)
    gc = fin.reshape(T, eg, el // eg).sum(2)
    ranked = np.where(gc > 0, gs, -np.inf)
    want = -np.sort(-ranked, axis=1)[:, :k]
    want = np.where(np.isfinite(want), want, np.nan)
    got = np.asarray(tv)
    if (np.isfinite(got) != np.isfinite(want)).any():
        fail("interpret-mode event topK smoke: NaN-rank pattern "
             "diverged from the XLA oracle")
    fin2 = np.isfinite(want)
    t_rel = float(np.max(np.abs(got[fin2] - want[fin2])
                         / np.maximum(np.abs(want[fin2]), 1e-6),
                         initial=0.0))
    log(f"interpret smoke: event topk fused-vs-XLA rel={t_rel:.2e}")
    if not t_rel < 1e-5:
        fail(f"interpret-mode event topK smoke diverged "
             f"(rel={t_rel:.2e})")


def _numpy_rate_sum(ts, vals, ids, steps):
    """Per-series, per-window iterator implementation — the reference's
    ChunkedRateFunction shape (binary search + per-window pass), single core."""
    S_, R_ = ts.shape
    T_ = len(steps)
    G_ = ids.max() + 1 if len(ids) else 1
    out = np.zeros((G_, T_))
    cnt = np.zeros((G_, T_))
    for s in range(S_):
        t_row, v_row = ts[s], vals[s]
        fin = np.isfinite(v_row)
        t_row, v_row = t_row[fin], v_row[fin]
        if len(t_row) < 2:
            continue
        corr = np.concatenate([[0.0], np.cumsum(np.maximum(
            v_row[:-1] - v_row[1:], 0.0))])
        v_adj = v_row + corr
        for j, st in enumerate(steps):
            lo = np.searchsorted(t_row, st - WINDOW_MS, side="right")
            hi = np.searchsorted(t_row, st, side="right")
            if hi - lo < 2:
                continue
            t1, t2 = t_row[lo], t_row[hi - 1]
            if t2 == t1:
                continue
            delta = v_adj[hi - 1] - v_adj[lo]
            n = hi - lo
            avg_dur = (t2 - t1) / (n - 1)
            ext_start = min(st - WINDOW_MS + avg_dur / 2, float(t1)) \
                if t1 - (st - WINDOW_MS) <= avg_dur * 1.1 else t1 - avg_dur / 2
            ext_end = max(st - avg_dur / 2, float(t2)) \
                if st - t2 <= avg_dur * 1.1 else t2 + avg_dur / 2
            rate = delta * ((ext_end - ext_start) / (t2 - t1)) / (WINDOW_MS / 1000.0)
            g = ids[s]
            out[g, j] += rate
            cnt[g, j] += 1
    return np.where(cnt > 0, out, np.nan)


if __name__ == "__main__":
    main()
