"""North-star benchmark: PromQL samples-scanned/sec on one chip.

Workload: the QueryInMemoryBenchmark-equivalent hot path (reference:
jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:45-249, scaled to
the BASELINE.json north-star config) — ``sum by (group)(rate(metric[5m]))``
over 1M series × 1h of samples, running the aligned-grid leaf kernel
(filodb_tpu/ops/grid.py): counter correction + windowed Prometheus rate +
grouped sum fused into one Pallas kernel.  This is the kernel the
device-resident serving path dispatches to when the layout invariant
holds; end-to-end served throughput is benchmarked separately in
benches/.

Protocol (see .claude/skills/verify/SKILL.md gotchas): data is generated
on-device from a scalar seed; the pipeline runs K statically-known
iterations, each forced by a ``float(...)`` readback; elapsed time subtracts
the measured 1-iteration variant so generation + RTT + readback cancel.
int32 timestamps / float32 values (TPU f64 is emulated).

Baseline: the reference publishes no absolute numbers and no JVM exists
in this environment (BASELINE.md), so ``vs_baseline`` is measured against
a multithreaded -O3 C++ implementation of the identical per-series /
per-window iterator workload (filodb_tpu/native/src/baseline.cpp — the
JVM-iterator-path proxy demanded by BASELINE.md's protocol), run on a
subsample and scaled per-sample.  Falls back to the single-core numpy
oracle below if no compiler is available.

Prints exactly ONE JSON line on stdout.
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


G = int(os.environ.get("FILODB_BENCH_GROUPS", 1_000))   # sum by (group)
PER = int(os.environ.get("FILODB_BENCH_PER_GROUP", 1_000))
S = G * PER                                             # real series
NB = int(os.environ.get("FILODB_BENCH_ROWS", 60))       # 1h at 1m resolution
ITERS = int(os.environ.get("FILODB_BENCH_ITERS", 40))
WINDOW_MS = 300_000                                     # rate(...[5m])
STEP_MS = 60_000
SUB = int(os.environ.get("FILODB_BENCH_NUMPY_SERIES", 2_000))
CPP_SUB = int(os.environ.get("FILODB_BENCH_CPP_SERIES", 100_000))
GL = 1_024                                              # lanes per group
T0 = 600_000


def _probe_backend(timeout_s: int):
    """Initialize the JAX backend under a watchdog.

    During an axon-tunnel outage the TPU plugin *hangs* in init rather
    than raising (round-4 BENCH artifact was lost to this).  Init runs in
    a daemon thread; a hang or error becomes a fast, explicit exit with a
    machine-readable JSON error line instead of a driver-side timeout.
    Backend init is process-global, so the main thread reuses the
    initialized backend afterwards.
    """
    import threading

    box = {}

    def probe():
        try:
            import jax
            box["devices"] = [str(d) for d in jax.devices()]
        except Exception as e:  # noqa: BLE001 — report any init failure
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=probe, daemon=True, name="backend-probe")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return f"JAX backend init timed out after {timeout_s}s (TPU tunnel down?)"
    return box.get("error")


def main():
    err = _probe_backend(int(os.environ.get("FILODB_BENCH_PROBE_TIMEOUT_S", "180")))
    if err is not None:
        log(f"TPU unavailable: {err}")
        print(json.dumps({
            "metric": "PromQL samples scanned/sec (rate()+sum-by)",
            "value": 0.0, "unit": "samples/sec", "vs_baseline": 0.0,
            "error": f"TPU unavailable: {err}",
        }))
        sys.stdout.flush()
        os._exit(3)   # probe thread may still be wedged in native init

    import jax
    import jax.numpy as jnp

    from filodb_tpu.ops.grid import GridQuery, rate_grid_grouped

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    B = ((NB + 7) // 8) * 8                 # sublane-pad the bucket axis
    S_pad = G * GL
    steps_np = np.arange(T0 + WINDOW_MS, T0 + NB * STEP_MS, STEP_MS,
                        dtype=np.int32)
    T = len(steps_np)
    K = WINDOW_MS // STEP_MS
    # The generated workload satisfies the dense-lane contract (regular
    # scrapes: every live lane finite over all used rows, pad lanes
    # all-NaN) — verified on the device data below before timing.  This
    # is the same specialization the device store auto-detects from its
    # per-block fill ranges when serving real ingested data.
    q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, is_rate=True,
                  dense=True)

    def gen_body(seed):
        """On-device aligned-grid gen ([B, S] time-major): row c holds
        the sample with ts in (T0+(c-1)*step, T0+c*step].  Each series
        is scraped at a CONSTANT per-lane phase within its bucket —
        strictly more general than the reference benchmark data, whose
        producer emits exact-cadence timestamps identical across series
        (TestTimeseriesProducer.scala:128: ``startTime + n/numTs *
        10000``).  The store proves this uniform-phase layout per lane
        from block fill stats and serves it with the no-ts-plane phase
        kernels (memstore/devicestore.py); per-sample-jittered data
        falls back to the ts-streaming dense kernels."""
        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        base = (jnp.arange(B, dtype=jnp.int32) * STEP_MS
                + T0 - STEP_MS)[:, None]
        # headroom below STEP_MS: the timing loop bumps phase by +i per
        # iteration (see pipeline) and phase must stay in (0, gstep]
        phase = jax.random.randint(k1, (1, S_pad), 1,
                                   STEP_MS - ITERS - 1, jnp.int32)
        ts = base + phase
        incr = jax.random.uniform(k2, (B, S_pad), jnp.float32, 0.0, 10.0)
        vals = jnp.cumsum(incr, axis=0)
        lane = jnp.arange(S_pad, dtype=jnp.int32) % GL
        mask = ((jnp.arange(B) < NB)[:, None]) & ((lane < PER)[None, :])
        # kernel contract: row 0 = first bucket of the first window
        return ts[1:], jnp.where(mask, vals, jnp.nan)[1:], phase[0]

    def pipeline(ts, vals, phase, bump):
        # the serving path reads back (sum, count) partials and applies
        # the count>0 mask host-side during the aggregator merge — the
        # kernel's deliverable is the two [G, T] partials.  The CSE-
        # defeating bump perturbs the [1, S] phase row (4 MB), NOT the
        # [B, S] values plane: serving reads RESIDENT values, and a
        # per-iteration ``vals + bump`` would materialize a fresh 250 MB
        # array each query — traffic the server never pays.
        return rate_grid_grouped(None, vals, int(steps_np[0]), q,
                                 group_lanes=GL, phase=phase + bump)

    def build(iters: int):
        def f(seed):
            ts, vals, phase = gen_body(seed)
            acc = jnp.float32(0.0)
            for i in range(iters):
                s, c = pipeline(ts, vals, phase, jnp.int32(i))
                acc = acc + s[0, 0] + s[G // 2, T // 2] + c[0, 0]
            return acc
        return jax.jit(f)

    # prove the dense-lane contract on the rows the kernel uses
    def check_dense(seed):
        _, vals, _ = gen_body(seed)
        fin_cnt = jnp.isfinite(vals[:T + K - 1]).sum(axis=0)
        return jnp.all((fin_cnt == 0) | (fin_cnt == T + K - 1))
    assert bool(jax.jit(check_dense)(0)), \
        "generated data violates the dense-lane contract"

    # the phase kernels must agree with the ts-streaming kernels on the
    # real device (CI exercises them in interpret mode only)
    def check_phase_equiv(seed):
        ts, vals, phase = gen_body(seed)
        s_ph, c_ph = rate_grid_grouped(None, vals, int(steps_np[0]), q,
                                       group_lanes=GL, phase=phase)
        s_ts, c_ts = rate_grid_grouped(ts, vals, int(steps_np[0]), q,
                                       group_lanes=GL)
        rel = jnp.abs(s_ph - s_ts) / jnp.maximum(jnp.abs(s_ts), 1e-6)
        return jnp.nanmax(jnp.where(c_ts > 0, rel, 0.0)), \
            jnp.max(jnp.abs(c_ph - c_ts))
    rel_err, cnt_err = jax.jit(check_phase_equiv)(0)
    rel_err, cnt_err = float(rel_err), float(cnt_err)
    log(f"phase-vs-ts kernel max rel err: {rel_err:.2e}; "
        f"count err: {cnt_err}")
    assert rel_err < 2e-5 and cnt_err == 0, \
        "phase kernel diverged from ts kernel"

    f_base, f_full = build(1), build(1 + ITERS)
    log("compiling (1 and %d iteration variants)..." % (1 + ITERS))
    _ = float(f_base(0))
    _ = float(f_full(0))

    def timed(f, reps=7):
        best = []
        for _ in range(reps):
            a = time.perf_counter()
            _ = float(f(0))
            best.append(time.perf_counter() - a)
        return float(np.median(best))

    log("timing...")
    t_base = timed(f_base)
    t_full = timed(f_full)
    elapsed = max(t_full - t_base, 1e-9)
    # row 0 is clipped to meet the kernel row contract: NB-1 real buckets
    samples_per_query = S * (NB - 1)
    tpu_rate = samples_per_query * ITERS / elapsed
    log(f"device: {tpu_rate:.3e} samples/sec "
        f"({ITERS} queries in {elapsed:.3f}s; base {t_base:.3f}s, "
        f"full {t_full:.3f}s)")

    # -- CPU baseline (C++ multithreaded JVM proxy) on a subsample ----------
    from filodb_tpu.native import baseline as cpp_baseline

    ts, vals, _phase = jax.jit(gen_body)(0)
    use_cpp = cpp_baseline.available()
    nsub = min(CPP_SUB if use_cpp else SUB, S)
    # real lanes (lane % GL < PER), walking whole groups first
    ngroups_needed = (nsub + PER - 1) // PER
    lanes = (np.arange(ngroups_needed)[:, None] * GL
             + np.arange(PER)[None, :]).ravel()[:nsub]
    lanes_j = jnp.asarray(lanes, dtype=jnp.int32)
    sub_ts = np.asarray(jax.device_get(ts[:, lanes_j])).astype(np.int64).T
    sub_vals = np.asarray(jax.device_get(vals[:, lanes_j])).astype(np.float64).T
    ids_np = np.zeros(nsub, dtype=np.int32)
    steps64 = steps_np.astype(np.int64)
    if use_cpp:
        nthreads = cpp_baseline.hw_threads()
        cpp_baseline.rate_sum(sub_ts[:64], sub_vals[:64], ids_np[:64], 1,
                              steps64, WINDOW_MS)       # warm (page-in)
        # best-of-3: this shared 1-core host swings >10x with co-tenant
        # load, and a slow baseline shot INFLATES vs_baseline — take the
        # least-contended run as the honest proxy of the machine
        np_elapsed = float("inf")
        for _ in range(3):
            a = time.perf_counter()
            cpp_out = cpp_baseline.rate_sum(sub_ts, sub_vals, ids_np, 1,
                                            steps64, WINDOW_MS)
            np_elapsed = min(np_elapsed, time.perf_counter() - a)
        np_rate = nsub * (NB - 1) / np_elapsed
        log(f"C++ baseline ({nthreads} threads): {np_rate:.3e} samples/sec "
            f"({nsub} series, best {np_elapsed:.3f}s of 3)")
        # cross-check vs the numpy oracle on a slice so the baseline can
        # never silently drift from the measured semantics
        ora = _numpy_rate_sum(sub_ts[:256], sub_vals[:256], ids_np[:256],
                              steps64)
        chk = cpp_baseline.rate_sum(sub_ts[:256], sub_vals[:256],
                                    ids_np[:256], 1, steps64, WINDOW_MS)
        assert np.allclose(ora, chk, rtol=1e-9, equal_nan=True), \
            "C++ baseline diverged from oracle"
    else:
        log(f"C++ baseline unavailable ({cpp_baseline.build_error()}); "
            "falling back to single-core numpy proxy")
        a = time.perf_counter()
        _numpy_rate_sum(sub_ts, sub_vals, ids_np, steps64)
        np_elapsed = time.perf_counter() - a
        np_rate = nsub * (NB - 1) / np_elapsed
        log(f"numpy proxy: {np_rate:.3e} samples/sec ({nsub} series, "
            f"{np_elapsed:.3f}s)")

    print(json.dumps({
        "metric": "PromQL samples scanned/sec (rate()+sum-by, "
                  f"{S} series, 1h range)",
        "value": round(tpu_rate, 1),
        "unit": "samples/sec",
        "vs_baseline": round(tpu_rate / np_rate, 2),
    }))


def _numpy_rate_sum(ts, vals, ids, steps):
    """Per-series, per-window iterator implementation — the reference's
    ChunkedRateFunction shape (binary search + per-window pass), single core."""
    S_, R_ = ts.shape
    T_ = len(steps)
    G_ = ids.max() + 1 if len(ids) else 1
    out = np.zeros((G_, T_))
    cnt = np.zeros((G_, T_))
    for s in range(S_):
        t_row, v_row = ts[s], vals[s]
        fin = np.isfinite(v_row)
        t_row, v_row = t_row[fin], v_row[fin]
        if len(t_row) < 2:
            continue
        corr = np.concatenate([[0.0], np.cumsum(np.maximum(
            v_row[:-1] - v_row[1:], 0.0))])
        v_adj = v_row + corr
        for j, st in enumerate(steps):
            lo = np.searchsorted(t_row, st - WINDOW_MS, side="right")
            hi = np.searchsorted(t_row, st, side="right")
            if hi - lo < 2:
                continue
            t1, t2 = t_row[lo], t_row[hi - 1]
            if t2 == t1:
                continue
            delta = v_adj[hi - 1] - v_adj[lo]
            n = hi - lo
            avg_dur = (t2 - t1) / (n - 1)
            ext_start = min(st - WINDOW_MS + avg_dur / 2, float(t1)) \
                if t1 - (st - WINDOW_MS) <= avg_dur * 1.1 else t1 - avg_dur / 2
            ext_end = max(st - avg_dur / 2, float(t2)) \
                if st - t2 <= avg_dur * 1.1 else t2 + avg_dur / 2
            rate = delta * ((ext_end - ext_start) / (t2 - t1)) / (WINDOW_MS / 1000.0)
            g = ids[s]
            out[g, j] += rate
            cnt[g, j] += 1
    return np.where(cnt > 0, out, np.nan)


if __name__ == "__main__":
    main()
