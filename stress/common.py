"""Shared helpers for the stress runners."""

from __future__ import annotations

import json
import sys
import time


def emit(metric: str, value, unit: str, **extra) -> None:
    print(json.dumps({"metric": metric,
                      "value": round(value, 1) if isinstance(value, float)
                      else value,
                      "unit": unit, **extra}), flush=True)


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def force_cpu_x64() -> None:
    """Stress runs are host-side: never touch the shared TPU tunnel."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


class Latencies:
    def __init__(self):
        self.samples: list[float] = []

    def time(self):
        t0 = time.perf_counter()
        return lambda: self.samples.append(time.perf_counter() - t0)

    def pct(self, p: float) -> float:
        if not self.samples:
            return float("nan")
        s = sorted(self.samples)
        return s[min(int(len(s) * p), len(s) - 1)]
