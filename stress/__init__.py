"""Stress harness: concurrent ingest+query soaks and failover drills
(capability match for the reference's stress/ module, reference:
stress/src/main/scala/filodb.stress/*.scala — IngestionStress,
InMemoryQueryStress, StreamingStress — and the standalone multi-jvm
failover specs).  Run ``python -m stress.run_all`` from the repo root;
each runner prints JSON metric lines and exits nonzero on any
correctness failure."""
