"""Two-node kill/failover drill with live ingest and queries.

Reference intent being ported: standalone/src/multi-jvm
ClusterSingletonFailoverSpec + IngestionAndRecoverySpec — two nodes
share a dataset's shards; one node is killed; the failure detector
declares it down, the shard manager reassigns its shards to the
survivor, which replays them from the (durable) ingest transport; the
query surface returns to full-coverage answers.

Topology: one durable broker; node A owns shards 0-1, node B owns 2-3;
A's planner dispatches B's shards over HTTP.  The driver plays the
membership/gossip role the reference delegates to Akka Cluster: it
heartbeats B into A's failure detector while B lives, stops when B is
killed, and resyncs A after reassignment.

Usage: python -m stress.failover_stress [--seconds 30] [--series 64]
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

from stress.common import emit, force_cpu_x64, log

BASE = 1_700_000_000_000


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--series", type=int, default=64)
    args = ap.parse_args(argv)

    force_cpu_x64()
    import tempfile

    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.ingest.broker import BrokerClient, BrokerProducer, \
        BrokerServer
    from filodb_tpu.standalone import FiloServer

    num_shards = 4
    broker = BrokerServer(data_dir=tempfile.mkdtemp(prefix="stress-broker-"))
    broker.start()
    client = BrokerClient(port=broker.port)
    producer = BrokerProducer(client, "prom", num_shards)

    spread = 2  # one shard key fans out over 2^2 = all 4 shards

    import socket as _socket

    def free_port() -> int:
        with _socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            return sk.getsockname()[1]

    # fixed ports, as a real deployment's config would have
    port_a, port_b = free_port(), free_port()

    def node_config(name, my_port, peer_name, peer_port):
        return {
            "node": name,
            "http-port": my_port,
            "status-poll-interval-s": 0.5,
            "datasets": [{"name": "prom", "num-shards": num_shards,
                          "min-num-nodes": 2, "schema": "gauge",
                          "spread": spread,
                          "source": {"factory": "kafka",
                                     "port": broker.port},
                          "store": {"groups-per-shard": 2,
                                    "flush-interval": "10s"}}],
            "peers": {peer_name: f"http://127.0.0.1:{peer_port}"},
        }

    srv_b = FiloServer(node_config("node-b", port_b, "node-a", port_a))
    srv_b.start()
    srv_a = FiloServer(node_config("node-a", port_a, "node-b", port_b))
    srv_a.start()

    # NO driver choreography: node-a is the leader (lowest name), each
    # node's StatusPoller gossips /__health — B adopts A's assignment
    # view and resyncs itself; A learns B is alive and assigns it
    # shards.  Wait for the views to converge on their own.
    srv_a.failure_detector.timeout_ms = 2_000
    srv_a.status_poller.interval_s = 0.5
    srv_b.status_poller.interval_s = 0.5
    mapper_a = srv_a.manager.mapper("prom")
    # hard cap, load-insensitive: the smoke suite runs this under heavy
    # CPU contention; fixed short windows made the drill flaky
    deadline = time.time() + 90
    while time.time() < deadline:
        shards_a = mapper_a.shards_for_node("node-a")
        shards_b = mapper_a.shards_for_node("node-b")
        if sorted(shards_a + shards_b) == list(range(num_shards)) \
                and sorted(srv_b.coordinator.ingestion["prom"]
                           .running_shards()) == sorted(shards_b) \
                and shards_b:
            break
        time.sleep(0.3)
    assert sorted(shards_a + shards_b) == list(range(num_shards)) \
        and shards_b \
        and sorted(srv_b.coordinator.ingestion["prom"].running_shards()) \
        == sorted(shards_b), \
        f"never converged: a={shards_a} b={shards_b} " \
        f"b_running={srv_b.coordinator.ingestion['prom'].running_shards()}"
    log(f"converged: node-a owns {shards_a}, node-b owns {shards_b}")

    # continuous per-shard production to the durable broker
    produced = [0]
    stop = threading.Event()

    from filodb_tpu.core.record import partition_hash, shard_key_hash
    from filodb_tpu.core.schemas import DatasetOptions
    opts = DatasetOptions()
    tags_of = {}
    route = {}
    for s in range(args.series):
        tags = {"_metric_": "fm", "inst": f"i{s}", "_ws_": "w", "_ns_": "n"}
        tags_of[s] = tags
        # the gateway's routing rule: bit-splice of shard-key and
        # partition hashes under the spread
        route[s] = mapper_a.ingestion_shard(
            shard_key_hash(tags, opts), partition_hash(tags, opts),
            spread) % num_shards
    assert len(set(route.values())) == num_shards, \
        f"series only landed on shards {set(route.values())}"

    def produce():
        tick = 0
        while not stop.is_set():
            for s in range(args.series):
                b = RecordBuilder(DEFAULT_SCHEMAS["gauge"],
                                  container_size=64 * 1024)
                b.add_series([BASE + tick * 1000],
                             [[float(s + tick)]], tags_of[s])
                for c in b.containers():
                    producer.publish(route[s], c)
            produced[0] += args.series
            tick += 1
            time.sleep(0.2)

    # the step grid must intersect the 5-min staleness window of the
    # produced samples (which walk forward from BASE one second per tick)
    qs = urllib.parse.urlencode({
        "query": 'count(fm{_ws_="w",_ns_="n"})',
        "start": BASE / 1000,
        "end": (BASE + 600_000) / 1000, "step": "15s"})
    url = f"http://127.0.0.1:{port_a}/promql/prom/api/v1/query_range?{qs}"

    def full_count():
        """count over all shards via node A; None on failure."""
        try:
            body = json.loads(urllib.request.urlopen(url, timeout=30).read())
            if body.get("status") != "success" or not body["data"]["result"]:
                return None
            return max(int(float(v)) for _, v in
                       body["data"]["result"][0]["values"])
        except Exception:  # noqa: BLE001
            return None

    pt = threading.Thread(target=produce, daemon=True)
    pt.start()

    # phase 1: both nodes up; poll UNTIL full coverage appears (hard
    # cap), then sample for the configured window — a loaded host must
    # delay the drill, never fail it
    ok_before = 0
    deadline = time.time() + max(args.seconds, 90)
    while time.time() < deadline and ok_before == 0:
        if full_count() == args.series:
            ok_before += 1
        else:
            time.sleep(0.3)
    assert ok_before > 0, "no successful full-coverage query before failover"
    window_end = time.time() + args.seconds / 3
    while time.time() < window_end:
        if full_count() == args.series:
            ok_before += 1
        time.sleep(0.3)
    log(f"phase 1: {ok_before} full-coverage queries with both nodes up")

    # phase 2: KILL node B; keep producing
    t_kill = time.time()
    srv_b.shutdown()
    log("node-b killed")
    # A's StatusPoller stops hearing from B -> failure detector declares
    # it down -> shards reassigned -> on_assignment_change resyncs A ->
    # A replays B's shards from the durable broker.  No driver help.
    recovered_at = None
    deadline = time.time() + max(args.seconds, 90)
    while time.time() < deadline:
        if full_count() == args.series:
            recovered_at = time.time()
            break
        time.sleep(0.3)
    assert recovered_at is not None, "never recovered full coverage"
    gap = recovered_at - t_kill
    owned = srv_a.manager.mapper("prom").shards_for_node("node-a")
    assert sorted(owned) == list(range(num_shards)), owned
    log(f"phase 2: full coverage restored {gap:.1f}s after kill; "
        f"node-a now owns {owned}")

    # phase 3: poll until post-failover correctness is observed (hard
    # cap), then sample the configured window
    ok_after = 0
    deadline = time.time() + max(args.seconds, 60)
    while time.time() < deadline and ok_after == 0:
        if full_count() == args.series:
            ok_after += 1
        else:
            time.sleep(0.3)
    window_end = time.time() + args.seconds / 3
    while time.time() < window_end:
        if full_count() == args.series:
            ok_after += 1
        time.sleep(0.3)
    stop.set()
    pt.join(timeout=10)
    assert ok_after > 0, "no successful queries after failover"

    emit("failover recovery gap", gap, "seconds",
         shards_taken_over=len([s for s in owned if s in shards_b]))
    emit("failover queries ok (before/after)", ok_before + ok_after,
         "queries", before=ok_before, after=ok_after)
    emit("failover rows produced", produced[0], "rows")
    srv_a.shutdown()
    broker.shutdown()
    log("PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
