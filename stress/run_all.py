"""Run every stress drill as a subprocess; fail if any fails.

Usage: python -m stress.run_all [--seconds 30]
Reference analog: running the stress/ apps (stress/src/main/scala)."""

import argparse
import pathlib
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent

RUNNERS = ["stress.ingest_query_stress", "stress.failover_stress"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=30.0)
    args = ap.parse_args(argv)
    ok = True
    for mod in RUNNERS:
        print(f"=== {mod} ===", file=sys.stderr, flush=True)
        proc = subprocess.run(
            [sys.executable, "-m", mod, "--seconds", str(args.seconds)],
            cwd=str(HERE.parent), timeout=900)
        ok = ok and proc.returncode == 0
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
