"""Concurrent ingest + query soak on one node at realistic cardinality.

Reference intent being ported: stress/IngestionStress.scala (sustained
concurrent writes, then read back and compare every cell),
InMemoryQueryStress.scala (many concurrent PromQL queries), and
jmh/QueryAndIngestBenchmark.scala:38 (queries while ingest continues).

One FiloServer, N producer threads pushing containers into the per-shard
queue streams, M query threads hammering the HTTP PromQL surface with a
query mix (raw count, sum(rate), quantile, label_values).  At the end:
drain, then verify per-series sample counts and values exactly match
what was produced — queries racing ingest/flush must never corrupt data.

Usage: python -m stress.ingest_query_stress [--seconds 20]
       [--series 2000] [--shards 4] [--query-threads 4]
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

from stress.common import Latencies, emit, force_cpu_x64, log

BASE = 1_700_000_000_000


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--series", type=int, default=2_000)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--query-threads", type=int, default=4)
    ap.add_argument("--producer-threads", type=int, default=2)
    args = ap.parse_args(argv)

    force_cpu_x64()
    from filodb_tpu.core.record import RecordBuilder, partition_hash, \
        shard_key_hash
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.standalone import FiloServer

    srv = FiloServer({
        "node": "stress-0",
        "datasets": [{"name": "prom", "num-shards": args.shards,
                      "schema": "gauge", "spread": 1,
                      "query": {"workers": 4, "max-queued": 512},
                      "store": {"groups-per-shard": 4,
                                "flush-interval": "5s"}}],
    })
    port = srv.start()
    opts = DatasetOptions()
    mapper = srv.manager.mapper("prom")
    schema = DEFAULT_SCHEMAS["gauge"]

    # per-series routing + bookkeeping
    tags_of = {}
    shard_of = {}
    for s in range(args.series):
        tags = {"_metric_": "stress_metric", "inst": f"i{s}",
                "job": f"j{s % 23}", "_ws_": "w", "_ns_": "n"}
        tags_of[s] = tags
        shard_of[s] = mapper.ingestion_shard(
            shard_key_hash(tags, opts), partition_hash(tags, opts),
            1) % args.shards
    produced = np.zeros(args.series, dtype=np.int64)
    stop = threading.Event()
    errors: list[str] = []

    def producer(worker: int):
        """Each worker owns a slice of series and appends batches of
        rows walking forward in time."""
        mine = [s for s in range(args.series)
                if s % args.producer_threads == worker]
        tick = 0
        rows_per_batch = 5
        while not stop.is_set():
            by_shard: dict[int, RecordBuilder] = {}
            for s in mine:
                b = by_shard.get(shard_of[s])
                if b is None:
                    b = by_shard[shard_of[s]] = RecordBuilder(
                        schema, opts, container_size=256 * 1024)
                t0 = BASE + tick * rows_per_batch * 1000
                ts = [t0 + r * 1000 for r in range(rows_per_batch)]
                vals = [float(s) + 0.001 * (tick * rows_per_batch + r)
                        for r in range(rows_per_batch)]
                b.add_series(ts, [vals], tags_of[s])
                produced[s] += rows_per_batch
            for shard, b in by_shard.items():
                for c in b.containers():
                    srv.stream_factory.stream_for("prom", shard).push(c)
            tick += 1
            time.sleep(0.01)

    QUERIES = [
        'count(stress_metric{_ws_="w",_ns_="n"})',
        'sum(rate(stress_metric{_ws_="w",_ns_="n"}[1m]))',
        'quantile(0.9, stress_metric{_ws_="w",_ns_="n"})',
        'sum by (job)(stress_metric{_ws_="w",_ns_="n"})',
    ]
    qcount = [0]
    lat = Latencies()

    def querier(worker: int):
        i = worker
        while not stop.is_set():
            q = QUERIES[i % len(QUERIES)]
            i += 1
            now_ms = BASE + int((time.time() - t_start) * 1000) + 60_000
            qs = urllib.parse.urlencode({
                "query": q, "start": (now_ms - 120_000) / 1000,
                "end": now_ms / 1000, "step": "5s"})
            done = lat.time()
            try:
                body = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/promql/prom/api/v1/"
                    f"query_range?{qs}", timeout=30).read())
                if body.get("status") != "success":
                    errors.append(f"query status {body}")
                    return
                qcount[0] += 1
            except Exception as e:  # noqa: BLE001
                errors.append(f"{q}: {e!r}")
                return
            finally:
                done()

    t_start = time.time()
    producers = [threading.Thread(target=producer, args=(w,), daemon=True)
                 for w in range(args.producer_threads)]
    queriers = [threading.Thread(target=querier, args=(w,), daemon=True)
                for w in range(args.query_threads)]
    for t in producers + queriers:
        t.start()
    time.sleep(args.seconds)
    stop.set()
    for t in producers + queriers:
        t.join(timeout=30)
    elapsed = time.time() - t_start

    # drain: every produced row must arrive
    total_produced = int(produced.sum())
    deadline = time.time() + 60
    while time.time() < deadline:
        ingested = sum(sh.stats.rows_ingested
                       for sh in srv.memstore.shards("prom"))
        if ingested >= total_produced:
            break
        time.sleep(0.1)
    ok = True
    if ingested != total_produced:
        log(f"FAIL: ingested {ingested} != produced {total_produced}")
        ok = False

    # cell-exact spot check (IngestionStress "compare every cell" intent):
    # verify 50 random series' full contents
    rng = np.random.default_rng(0)
    check = rng.choice(args.series, size=min(50, args.series), replace=False)
    for s in check:
        sh = srv.memstore.get_shard("prom", shard_of[int(s)])
        pids = [pid for pid, p in sh.partitions.items()
                if p.tags.get("inst") == f"i{s}"]
        if len(pids) != 1:
            log(f"FAIL: series i{s}: {len(pids)} partitions")
            ok = False
            continue
        ts, vals = sh.partitions[pids[0]].read_range(
            0, np.iinfo(np.int64).max)
        n = int(produced[int(s)])
        if len(ts) != n:
            log(f"FAIL: series i{s}: {len(ts)} rows != produced {n}")
            ok = False
            continue
        want = float(s) + 0.001 * np.arange(n)
        if not np.allclose(vals, want, atol=1e-9):
            log(f"FAIL: series i{s}: value mismatch")
            ok = False
    if errors:
        log(f"FAIL: {len(errors)} query errors; first: {errors[0]}")
        ok = False

    # eviction-under-soak (round-5 VERDICT #9): flush + evict a slice of
    # partitions on every shard right after the soak — the deferred
    # index applier may still be draining adds for series the eviction
    # removes.  The index must stay consistent: the applier queue fully
    # drained after one lookup, every series (live or evicted) still
    # indexed, and no ghost/duplicate ids.
    from filodb_tpu.core.filters import ColumnFilter, Equals
    evicted_total = 0
    for sh in srv.memstore.shards("prom"):
        sh.flush_all()
        # everything stopped producing: mark end-times so the eviction
        # ordering has victims (like the reference's stopped-series pass)
        sh.mark_stopped_series(now_ms=np.iinfo(np.int64).max // 2,
                               stale_ms=0)
        evicted_total += sh.evict_partitions(max(1, sh.num_partitions // 4))
    if evicted_total == 0:
        log("FAIL: eviction-under-soak evicted nothing")
        ok = False
    seen_ids = 0
    for sh in srv.memstore.shards("prom"):
        res = sh.lookup_partitions(
            [ColumnFilter("_metric_", Equals("stress_metric"))], 0, 2**62)
        ids = list(res.part_ids)
        if len(ids) != len(set(ids)):
            log(f"FAIL: duplicate part ids after eviction on "
                f"shard {sh.shard_num}")
            ok = False
        seen_ids += len(ids)
        pending = len(sh.index._pending_adds)
        if pending:
            log(f"FAIL: index applier queue not drained after eviction "
                f"(shard {sh.shard_num}: {pending} pending)")
            ok = False
    # a memory-only shard removes evicted series from the index (the
    # ODP shard variant keeps them; covered by tests/test_persistence):
    # exactly the evicted count must disappear, no more, no less
    if seen_ids != args.series - evicted_total:
        log(f"FAIL: index inconsistent under eviction: {seen_ids} != "
            f"{args.series} - {evicted_total}")
        ok = False
    emit("stress evicted under soak", evicted_total, "partitions",
         indexed_after=seen_ids)

    flushes = sum(sh.stats.flushes_done for sh in srv.memstore.shards("prom"))
    emit("stress ingest throughput", total_produced / elapsed, "rows/sec",
         series=args.series, shards=args.shards, seconds=round(elapsed, 1))
    emit("stress queries completed", qcount[0], "queries",
         qps=round(qcount[0] / elapsed, 1))
    emit("stress query p50 latency", lat.pct(0.50) * 1000, "ms")
    emit("stress query p99 latency", lat.pct(0.99) * 1000, "ms",
         note="includes first-shape XLA compiles")
    emit("stress query errors", len(errors), "errors")
    emit("stress verified series cells", len(check), "series",
         flushes_during=flushes)
    srv.shutdown()
    log("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
