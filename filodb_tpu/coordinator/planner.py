"""SingleClusterPlanner: LogicalPlan -> ExecPlan with shard pruning.

Mirrors the reference's planner walk (reference: coordinator/.../queryplanner/
SingleClusterPlanner.scala:36): shard pruning via shard-key filters + spread
(:106-136), per-shard MultiSchemaPartitionsExec leaves (:338-361),
hierarchical aggregation reduce with sqrt grouping at >=16 children
(:223-258), transformers attached per logical node.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

from filodb_tpu.core.filters import ColumnFilter, equals_value
from filodb_tpu.core.record import stable_hash32
from filodb_tpu.core.schemas import DatasetOptions
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import (BinaryJoinExec, DistConcatExec, ExecPlan,
                                   IN_PROCESS, LabelValuesDistConcatExec,
                                   LabelValuesExec, MultiSchemaPartitionsExec,
                                   PartKeysDistConcatExec, PartKeysExec,
                                   PlanDispatcher, ReduceAggregateExec,
                                   ScalarBinaryOperationExec,
                                   ScalarFixedDoubleExec, SetOperatorExec,
                                   TimeScalarGeneratorExec)
from filodb_tpu.query.model import QueryContext
from filodb_tpu.query.transformers import (AbsentFunctionMapper,
                                           AggregateMapReduce,
                                           AggregatePresenter,
                                           InstantVectorFunctionMapper,
                                           MiscellaneousFunctionMapper,
                                           PeriodicSamplesMapper,
                                           ScalarFunctionMapper,
                                           ScalarOperationMapper,
                                           SortFunctionMapper,
                                           VectorFunctionMapper)


def spread_provider_from_config(assignments, default: int):
    """Config-driven per-shard-key spread overrides (reference:
    filodb-defaults.conf ``spread-assignment`` applied via
    QueryActor.scala:70-85 applySpreadProvider): each entry maps
    concrete shard-key values to a spread; the first rule whose keys all
    match the query's shard-key filter values wins, else the default.
    Returns a callable usable as SingleClusterPlanner.spread_provider."""
    rules = [({str(k): str(v) for k, v in a.get("keys", {}).items()},
              int(a["spread"])) for a in assignments]

    def provider(values: dict) -> int:
        for keys, sp in rules:
            if keys and all(values.get(k) == v for k, v in keys.items()):
                return sp
        return default

    return provider



class QueryPlanner:
    """Planner interface (reference: queryplanner/QueryPlanner.scala:16)."""

    def materialize(self, plan: lp.LogicalPlan,
                    qctx: Optional[QueryContext] = None) -> ExecPlan:
        raise NotImplementedError


class SingleClusterPlanner(QueryPlanner):
    def __init__(self, dataset: str, shard_mapper: ShardMapper,
                 options: Optional[DatasetOptions] = None,
                 spread_default: int = 1,
                 spread_provider: Optional[Callable[[dict], int]] = None,
                 dispatcher_for_shard: Optional[
                     Callable[[int], PlanDispatcher]] = None,
                 hierarchical_reduce_at: int = 16,
                 min_time_range_for_split_ms: Optional[int] = None,
                 split_size_ms: Optional[int] = None,
                 mesh_engine_provider: Optional[Callable[[], object]] = None,
                 mesh_fused: bool = True):
        self.dataset = dataset
        self.mapper = shard_mapper
        self.options = options or DatasetOptions()
        self.spread_default = spread_default
        self.spread_provider = spread_provider
        self.dispatcher_for_shard = dispatcher_for_shard or (lambda s: IN_PROCESS)
        self.hierarchical_reduce_at = hierarchical_reduce_at
        # time splitting (reference: SingleClusterPlanner.scala:61-104 —
        # long queries split into sub-ranges and stitched)
        self.min_time_range_for_split_ms = min_time_range_for_split_ms
        self.split_size_ms = split_size_ms or min_time_range_for_split_ms
        # ICI-collective serving path: when set, a distributive aggregate
        # over local shards fuses into ONE SPMD mesh program
        # (parallel/meshexec.py) instead of per-shard children + host
        # reduce; remote shards keep HTTP dispatch alongside
        self.mesh_engine_provider = mesh_engine_provider
        # mesh query fabric (ISSUE 18): when every child shard of an
        # aggregation is mesh-resident on this host, emit MeshReduceExec
        # as the plan ROOT — ONE compiled launch incl. the cross-shard
        # psum and present, one [G, T] readback.  Off => the PR 17 form
        # (MeshAggregateExec partials under a host ReduceAggregateExec)
        self.mesh_fused = mesh_fused

    # -- topology snapshot (ISSUE 13) ---------------------------------------

    def _topology(self, qctx: QueryContext):
        """The mapper topology THIS query plans against, captured once
        per (query, dataset) and reused for every fan-out and leaf
        decision in the materialize pass.  A live shard split commits by
        swapping the mapper's topology; a query that read the old
        num_shards for fan-out must also use the old (no-exclusion)
        leaf stamps — mixing the two either drops or double-counts the
        migrated half.  Stored on the qctx (not a wire field) so the
        rollup router and result cache, which re-enter materialize with
        the same qctx, stay on one consistent view per dataset."""
        topos = getattr(qctx, "_topologies", None)
        if topos is None:
            topos = qctx._topologies = {}
        topo = topos.get(self.dataset)
        if topo is None:
            topo = topos[self.dataset] = self.mapper.topology
        return topo

    # -- shard pruning (reference :106-136) ---------------------------------

    def shards_from_filters(self, filters: Sequence[ColumnFilter],
                            qctx: QueryContext) -> list[int]:
        shard_cols = self.options.shard_key_columns
        values = {}
        for col in shard_cols:
            v = equals_value(filters, col)
            if col == self.options.metric_column:
                v = v if v is not None else equals_value(filters, "_metric_")
            if v is None:
                return self._all_shards(qctx)
            values[col] = v
        # per-query spread override wins over the provider (reference:
        # QueryActor.scala:70-85 — explicit spreadOverride beats the func)
        spread = self.spread_default
        if self.spread_provider is not None:
            spread = self.spread_provider(values)
        if qctx.spread is not None:
            spread = qctx.spread
        shash = self._shard_key_hash(values)
        topo = self._topology(qctx)
        shards = topo.query_shards(shash, spread)
        active = set(self.mapper.active_shards(range(topo.num_shards)))
        if active:
            shards = [s for s in shards if s in active] or shards
        return sorted(set(shards))

    def _shard_key_hash(self, values: dict) -> int:
        parts = []
        for col in self.options.shard_key_columns:
            v = values.get(col, "")
            for suffix in self.options.ignore_shard_key_column_suffixes.get(
                    col, ()):
                if v.endswith(suffix):
                    v = v[: -len(suffix)]
                    break
            parts.append(v)
        return stable_hash32("\x00".join(parts).encode())

    def _all_shards(self, qctx: QueryContext) -> list[int]:
        topo = self._topology(qctx)
        active = self.mapper.active_shards(range(topo.num_shards))
        return active if active else list(range(topo.num_shards))

    def plan_is_local(self, plan: lp.LogicalPlan,
                      qctx: QueryContext) -> bool:
        """True when every shard this plan would touch dispatches
        in-process — the result cache (query/resultcache.py) only
        memoizes plans whose chunk state it can probe locally."""
        for filters in lp.raw_series_filters(plan):
            for s in self.shards_from_filters(list(filters), qctx):
                if self.dispatcher_for_shard(s) is not IN_PROCESS:
                    return False
        return True

    # -- materialization ----------------------------------------------------

    def materialize(self, plan, qctx=None) -> ExecPlan:
        qctx = qctx or QueryContext()
        split = self._maybe_time_split(plan, qctx)
        if split is not None:
            return split
        return self._walk(plan, qctx)

    def _maybe_time_split(self, plan, qctx) -> Optional[ExecPlan]:
        """Split a long periodic query into sequential step-aligned
        sub-ranges and stitch (reference: time-splitting
        SingleClusterPlanner.scala:61-104 +
        SplitLocalPartitionDistConcatExec; sub-plans run sequentially —
        parallel_children=False — to bound peak memory)."""
        if self.min_time_range_for_split_ms is None:
            return None
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return None
        try:
            start, step, end = lp.time_range(plan)
        except ValueError:
            return None
        if end - start < self.min_time_range_for_split_ms:
            return None
        from filodb_tpu.coordinator.planners import copy_with_time_range
        from filodb_tpu.query.exec import StitchRvsExec
        steps_per_split = max(self.split_size_ms // step, 1)
        children = []
        t = start
        while t <= end:
            sub_end = min(t + (steps_per_split - 1) * step, end)
            children.append(self._walk(
                copy_with_time_range(plan, t, sub_end), qctx))
            t = sub_end + step
        if len(children) == 1:
            return children[0]
        # sequential sub-plans, like the reference's split path
        return StitchRvsExec(children, qctx, parallel_children=False)

    def _walk(self, plan, qctx) -> ExecPlan:
        if isinstance(plan, lp.PeriodicSeries):
            return self._periodic(plan.raw_series, qctx, plan.start_ms,
                                  plan.step_ms, plan.end_ms,
                                  offset=plan.offset_ms or 0)
        if isinstance(plan, lp.PeriodicSeriesWithWindowing):
            return self._periodic(plan.series, qctx, plan.start_ms,
                                  plan.step_ms, plan.end_ms,
                                  window=plan.window_ms,
                                  function=plan.function,
                                  args=plan.function_args,
                                  offset=plan.offset_ms or 0)
        if isinstance(plan, lp.Aggregate):
            return self._aggregate(plan, qctx)
        if isinstance(plan, lp.BinaryJoin):
            return self._binary_join(plan, qctx)
        if isinstance(plan, lp.ScalarVectorBinaryOperation):
            inner = self._walk(plan.vector, qctx)
            scalar = self._scalar_operand(plan.scalar_arg, qctx)
            inner.add_transformer(ScalarOperationMapper(
                plan.operator.name, scalar, plan.scalar_is_lhs,
                plan.bool_mode))
            return inner
        if isinstance(plan, lp.ApplyInstantFunction):
            fused = self._maybe_mesh_hist_quantile(plan, qctx)
            if fused is not None:
                return fused
            inner = self._walk(plan.vectors, qctx)
            args = tuple(self._scalar_operand(a, qctx)
                         if isinstance(a, lp.LogicalPlan) else a
                         for a in plan.function_args)
            inner.add_transformer(InstantVectorFunctionMapper(plan.function,
                                                              args))
            return inner
        if isinstance(plan, lp.ApplyMiscellaneousFunction):
            inner = self._walk(plan.vectors, qctx)
            inner.add_transformer(MiscellaneousFunctionMapper(
                plan.function, plan.string_args))
            return inner
        if isinstance(plan, lp.ApplySortFunction):
            inner = self._walk(plan.vectors, qctx)
            inner.add_transformer(SortFunctionMapper(plan.function))
            return inner
        if isinstance(plan, lp.ApplyAbsentFunction):
            inner = self._walk(plan.vectors, qctx)
            inner.add_transformer(AbsentFunctionMapper(
                plan.filters, plan.start_ms, plan.step_ms, plan.end_ms))
            return inner
        if isinstance(plan, lp.ScalarVaryingDoublePlan):
            inner = self._walk(plan.vectors, qctx)
            inner.add_transformer(ScalarFunctionMapper())
            return inner
        if isinstance(plan, lp.ScalarTimeBasedPlan):
            return TimeScalarGeneratorExec(plan.function, plan.start_ms,
                                           plan.step_ms, plan.end_ms,
                                           query_context=qctx)
        if isinstance(plan, lp.ScalarFixedDoublePlan):
            return ScalarFixedDoubleExec(plan.scalar, plan.start_ms,
                                         plan.step_ms, plan.end_ms,
                                         query_context=qctx)
        if isinstance(plan, lp.ScalarBinaryOperation):
            lhs = plan.lhs if isinstance(plan.lhs, (int, float)) \
                else self._walk(plan.lhs, qctx)
            rhs = plan.rhs if isinstance(plan.rhs, (int, float)) \
                else self._walk(plan.rhs, qctx)
            return ScalarBinaryOperationExec(plan.operator, lhs, rhs,
                                             plan.start_ms, plan.step_ms,
                                             plan.end_ms, query_context=qctx)
        if isinstance(plan, lp.VectorPlan):
            inner = self._walk(plan.scalars, qctx)
            inner.add_transformer(VectorFunctionMapper())
            return inner
        if isinstance(plan, lp.LabelValues):
            shards = self._all_shards(qctx)
            children = [LabelValuesExec(self.dataset, s, plan.label_names,
                                        plan.filters, plan.start_ms,
                                        plan.end_ms, qctx,
                                        self.dispatcher_for_shard(s))
                        for s in shards]
            return LabelValuesDistConcatExec(children, qctx)
        if isinstance(plan, lp.SeriesKeysByFilters):
            shards = self.shards_from_filters(plan.filters, qctx)
            topo = self._topology(qctx)
            children = [PartKeysExec(self.dataset, s, plan.filters,
                                     plan.start_ms, plan.end_ms, qctx,
                                     self.dispatcher_for_shard(s),
                                     reshard_to=topo.parent_exclusion(s))
                        for s in shards]
            return PartKeysDistConcatExec(children, qctx)
        if isinstance(plan, lp.RawChunkMeta):
            from filodb_tpu.query.exec import SelectChunkInfosExec
            shards = self.shards_from_filters(plan.filters, qctx)
            children = [SelectChunkInfosExec(self.dataset, s, plan.filters,
                                             plan.start_ms, plan.end_ms,
                                             qctx,
                                             self.dispatcher_for_shard(s))
                        for s in shards]
            return DistConcatExec(children, qctx)
        if isinstance(plan, lp.RawSeries):
            # bare raw selector (remote read / RawSeries API): per-shard
            # leaf scans with no periodic mapper, concatenated (reference:
            # SelectRawPartitionsExec without transformers)
            shards = self.shards_from_filters(plan.filters, qctx)
            topo = self._topology(qctx)
            column = plan.columns[0] if plan.columns else None
            children = [MultiSchemaPartitionsExec(
                self.dataset, s, plan.filters,
                plan.range_selector.from_ms, plan.range_selector.to_ms,
                column=column, query_context=qctx,
                dispatcher=self.dispatcher_for_shard(s),
                reshard_to=topo.parent_exclusion(s))
                for s in shards]
            return DistConcatExec(children, qctx)
        raise ValueError(f"cannot materialize {type(plan).__name__}")

    def _scalar_operand(self, plan, qctx):
        """Scalar argument: plain float for fixed scalars, an ExecPlan
        evaluated at run time otherwise (reference: FuncArgs/
        ExecPlanFuncArgs, ExecPlan.scala:287-335)."""
        if isinstance(plan, (int, float)):
            return float(plan)
        if isinstance(plan, lp.ScalarFixedDoublePlan):
            return plan.scalar
        return self._walk(plan, qctx)

    def _periodic(self, raw: lp.RawSeries, qctx, start, step, end,
                  window=None, function=None, args=(), offset=0,
                  shards=None) -> ExecPlan:
        if shards is None:
            shards = self.shards_from_filters(raw.filters, qctx)
        topo = self._topology(qctx)
        column = raw.columns[0] if raw.columns else None
        children = []
        for s in shards:
            leaf = MultiSchemaPartitionsExec(
                self.dataset, s, raw.filters,
                raw.range_selector.from_ms, raw.range_selector.to_ms,
                column=column, query_context=qctx,
                dispatcher=self.dispatcher_for_shard(s),
                reshard_to=topo.parent_exclusion(s))
            leaf.add_transformer(PeriodicSamplesMapper(
                start, step, end, window_ms=window, function=function,
                function_args=args, offset_ms=offset))
            children.append(leaf)
        return DistConcatExec(children, qctx)

    def _aggregate(self, plan: lp.Aggregate, qctx) -> ExecPlan:
        fused = self._maybe_mesh_aggregate(plan, qctx)
        if fused is not None:
            return fused
        inner = self._walk(plan.vectors, qctx)
        mapred = AggregateMapReduce(plan.operator, plan.params, plan.by,
                                    plan.without)
        if isinstance(inner, DistConcatExec):
            # push map-reduce into each shard-child; reduce above (reference
            # :223-258 removes the DistConcat and reduces directly)
            children = list(inner.children)
            for c in children:
                c.add_transformer(mapred)
            children = self._hierarchical_reduce(children, plan, qctx)
            root = ReduceAggregateExec(children, plan.operator, plan.params,
                                       qctx)
        else:
            inner.add_transformer(mapred)
            root = ReduceAggregateExec([inner], plan.operator, plan.params,
                                       qctx)
        root.add_transformer(AggregatePresenter(plan.operator, plan.params))
        return root

    def _maybe_mesh_aggregate(self, plan: lp.Aggregate, qctx
                              ) -> Optional[ExecPlan]:
        """Fuse ``agg(range_fn(selector[w]))`` over the LOCAL shards into
        one SPMD mesh program with psum reduce (parallel/meshexec.py);
        remote shards stay HTTP-dispatched children of the same
        ReduceAggregateExec.  Applies only when a mesh engine is
        configured and the shape is the distributive hot path."""
        if self.mesh_engine_provider is None:
            return None
        from filodb_tpu.parallel.meshexec import (MeshAggregateExec,
                                                  MeshReduceExec,
                                                  mesh_supported)
        inner = plan.vectors
        if isinstance(inner, lp.PeriodicSeriesWithWindowing):
            raw, window, function = inner.series, inner.window_ms, \
                inner.function
            args = inner.function_args
        elif isinstance(inner, lp.PeriodicSeries):
            raw, window, function, args = inner.raw_series, None, None, ()
        else:
            return None
        if not isinstance(raw, lp.RawSeries) or raw.columns:
            return None
        if not mesh_supported(plan.operator, function, plan.params):
            return None
        shards = self.shards_from_filters(raw.filters, qctx)
        topo = self._topology(qctx)
        if any(topo.parent_exclusion(s) for s in shards):
            # a split parent must slice off its migrated half at scan
            # time; the fused mesh program stages whole grids and has no
            # per-series exclusion — fall back to per-shard leaves until
            # the split retires (perf-only, bounded by the grace window)
            return None
        # which resident copy feeds the mesh: shards whose dispatcher is
        # IN_PROCESS always qualify.  Replicated shards (rf>1 routes
        # through ReplicaDispatcher, never IN_PROCESS) may join ONLY
        # when that makes EVERY child shard local and the fused root
        # eligible: the dispatcher factory's ``mesh_feed`` hook says the
        # local copy is the ``ReplicaSet.pick`` primary, so the
        # all-local fused serve IS the pick routing for every leg and
        # the reduce tree stays whole on every node that fuses.  A
        # partial mix of mesh legs and dispatched legs is deliberately
        # never built from feed shards — each replica-holding node would
        # regroup the float reduce differently and cross-node answers
        # would drift by summation order mid-failover
        # (tests/test_split_e2e.py's bit-equality contract).
        local = [s for s in shards
                 if self.dispatcher_for_shard(s) is IN_PROCESS]
        if self.mesh_fused and len(local) < len(shards):
            feed = getattr(self.dispatcher_for_shard, "mesh_feed", None)
            if feed is not None:
                fed = [s for s in shards if s in set(local) or feed(s)]
                if len(fed) == len(shards):
                    local = fed
        remote = [s for s in shards if s not in local]
        if len(local) < 2:
            return None   # nothing to fuse; per-shard path is simpler
        engine = self.mesh_engine_provider()
        # every child shard mesh-resident here + fabric on => the fused
        # root IS the whole plan (it returns PRESENTED batches)
        fuse_root = self.mesh_fused and not remote
        node_cls = MeshReduceExec if fuse_root else MeshAggregateExec
        mesh_child = node_cls(
            self.dataset, local, raw.filters,
            raw.range_selector.from_ms, raw.range_selector.to_ms,
            inner.start_ms, inner.step_ms, inner.end_ms, plan.operator,
            window_ms=window, function=function, function_args=args,
            offset_ms=inner.offset_ms or 0, by=plan.by,
            without=plan.without, params=plan.params, query_context=qctx,
            engine=engine, mapper=self.mapper,
            planned_generation=topo.generation)
        if fuse_root:
            return mesh_child
        # remote shards: the ordinary per-shard construction (_periodic
        # builds leaf+mapper exactly as the non-mesh path would)
        mapred = AggregateMapReduce(plan.operator, plan.params, plan.by,
                                    plan.without)
        remote_children: list[ExecPlan] = []
        if remote:
            concat = self._periodic(raw, qctx, inner.start_ms,
                                    inner.step_ms, inner.end_ms,
                                    window=window, function=function,
                                    args=args,
                                    offset=inner.offset_ms or 0,
                                    shards=remote)
            remote_children = list(concat.children)
            for c in remote_children:
                c.add_transformer(mapred)
            # same bounded fan-in the per-shard path gets (ref :244-258)
            remote_children = self._hierarchical_reduce(remote_children,
                                                        plan, qctx)
        root = ReduceAggregateExec([mesh_child] + remote_children,
                                   plan.operator, plan.params, qctx)
        root.add_transformer(AggregatePresenter(plan.operator, plan.params))
        return root

    def _maybe_mesh_hist_quantile(self, plan: lp.ApplyInstantFunction,
                                  qctx) -> Optional[ExecPlan]:
        """``histogram_quantile(phi, sum(..h..))`` with a static phi over
        an all-mesh-resident sum folds the quantile into the fused root:
        the cross-shard merge stays PRE-quantile (on-device bucket psum)
        and the interpolation runs inside the same device program —
        quantile-of-summed-buckets is the only cluster-wide-legal order,
        so the phi epilogue must ride the fused program, not a host
        mapper over per-shard quantiles."""
        if plan.function != lp.InstantFunctionId.HISTOGRAM_QUANTILE:
            return None
        if len(plan.function_args) != 1:
            return None
        phi = plan.function_args[0]
        if isinstance(phi, lp.ScalarFixedDoublePlan):
            phi = phi.scalar
        if not isinstance(phi, (int, float)):
            return None      # runtime-scalar phi: host mapper path
        inner = plan.vectors
        if not isinstance(inner, lp.Aggregate) \
                or inner.operator is not lp.AggregationOperator.SUM \
                or inner.params:
            return None
        root = self._maybe_mesh_aggregate(inner, qctx)
        from filodb_tpu.parallel.meshexec import MeshReduceExec
        if not isinstance(root, MeshReduceExec):
            return None      # not fully fusable; plain walk re-plans it
        root.hist_phi = float(phi)
        return root

    def _hierarchical_reduce(self, children, plan, qctx):
        """sqrt-group intermediate reduces for wide fan-outs (reference
        SingleClusterPlanner.scala:244-258)."""
        if len(children) < self.hierarchical_reduce_at:
            return children
        groups = max(int(math.sqrt(len(children))), 1)
        size = math.ceil(len(children) / groups)
        return [ReduceAggregateExec(children[i:i + size], plan.operator,
                                    plan.params, qctx)
                for i in range(0, len(children), size)]

    def _binary_join(self, plan: lp.BinaryJoin, qctx) -> ExecPlan:
        lhs = self._walk(plan.lhs, qctx)
        rhs = self._walk(plan.rhs, qctx)
        lhs_children = list(lhs.children) if isinstance(lhs, DistConcatExec) \
            else [lhs]
        rhs_children = list(rhs.children) if isinstance(rhs, DistConcatExec) \
            else [rhs]
        children = lhs_children + rhs_children
        if plan.operator.is_set_op:
            return SetOperatorExec(children, len(lhs_children), plan.operator,
                                   plan.on, plan.ignoring, qctx)
        return BinaryJoinExec(children, len(lhs_children), plan.operator,
                              plan.cardinality, plan.on, plan.ignoring,
                              plan.include, qctx, bool_mode=plan.bool_mode)
