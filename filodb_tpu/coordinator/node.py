"""Per-node coordination: ingestion lifecycle + dataset wiring.

Capability match for the reference's per-node actors (reference:
coordinator/src/main/scala/filodb.coordinator/NodeCoordinatorActor.scala:47
— creates per-dataset ingestion/query handlers; IngestionActor.scala:57 —
resync to assigned shards (:113-167), startIngestion = memStore.setup +
recoverIndex + checkpoint read -> recovery with progress events (:293) ->
normalIngestion (:236), stop/teardown).  Actors become plain objects +
one ingestion thread per shard; shard events flow to the ShardManager's
event hub instead of an Akka event stream.
"""

from __future__ import annotations

import threading
import traceback
from typing import Callable, Optional, Sequence

from filodb_tpu.coordinator.cluster import (IngestionError, IngestionStarted,
                                            IngestionStopped,
                                            RecoveryInProgress, ShardEvent)
from filodb_tpu.core.schemas import Schemas
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.ingest.stream import IngestionStreamFactory
from filodb_tpu.memstore.memstore import TimeSeriesMemStore


class IngestionCoordinator:
    """Drives one dataset's shard ingestion on this node (reference:
    IngestionActor)."""

    def __init__(self, node: str, dataset: str, schemas: Schemas,
                 memstore: TimeSeriesMemStore,
                 stream_factory: IngestionStreamFactory,
                 config: Optional[StoreConfig] = None,
                 event_sink: Optional[Callable[[ShardEvent], None]] = None,
                 recovery_report_interval: int = 10,
                 group_head_fn: Optional[Callable[[int], int]] = None):
        self.node = node
        self.dataset = dataset
        self.schemas = schemas
        self.memstore = memstore
        self.stream_factory = stream_factory
        self.config = config
        self.event_sink = event_sink or (lambda e: None)
        self.recovery_report_interval = recovery_report_interval
        # replica-group promotion gate (ISSUE 7): shard -> the group's
        # gossiped ingest head.  A recovering replica stays RECOVERY
        # until its own offset reaches max(local checkpoint head, group
        # head) — so a rejoining node is not promoted to Active while a
        # caught-up peer is still measurably ahead.  None = rf=1
        # behavior (local checkpoint head only).
        self.group_head_fn = group_head_fn
        self._threads: dict[int, threading.Thread] = {}
        self._stops: dict[int, threading.Event] = {}
        self._streams: dict[int, object] = {}  # live stream per shard for teardown
        self._lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    def resync(self, assigned_shards: Sequence[int]) -> None:
        """Reconcile running shards with the assignment (reference:
        IngestionActor.resync :113-167): start missing, stop extras."""
        with self._lock:
            running = set(self._threads)
        target = set(assigned_shards)
        for s in sorted(target - running):
            self.start_ingestion(s)
        for s in sorted(running - target):
            self.stop_ingestion(s)

    def start_ingestion(self, shard: int, blocking: bool = False) -> None:
        """setup -> recover index -> checkpointed recovery -> normal
        ingestion (reference: startIngestion :170, doRecovery :293).

        The memstore SETUP runs synchronously here, before the ingest
        thread spawns: a query dispatched right after assignment must
        find the shard registered (empty, possibly still recovering) —
        never race an async setup into 'shard not set up' failures."""
        stop = threading.Event()
        with self._lock:
            if shard in self._threads:
                return
            # has_shard+setup under the lock: two concurrent starts for the
            # same shard would otherwise both pass the check and the loser
            # raise ValueError out of setup (round-4 ADVICE). The except
            # keeps repeat starts idempotent even against setups from
            # OUTSIDE this ingester (tests / manual admin calls).
            if not self.memstore.has_shard(self.dataset, shard):
                try:
                    self.memstore.setup(self.dataset, self.schemas, shard,
                                        self.config)
                except ValueError:
                    # tolerated ONLY as the already-set-up race (setups
                    # from outside this ingester); a genuine setup
                    # failure must not register a dead ingest thread
                    if not self.memstore.has_shard(self.dataset, shard):
                        raise
            self._stops[shard] = stop
            if blocking:
                self._threads[shard] = threading.current_thread()
            else:
                t = threading.Thread(target=self._run_shard,
                                     args=(shard, stop),
                                     name=f"ingest-{self.dataset}-{shard}",
                                     daemon=True)
                self._threads[shard] = t
        if blocking:
            # adopt the shard's ingest-thread identity for the duration so
            # the single-writer assertions hold in blocking mode too
            cur = threading.current_thread()
            old_name = cur.name
            cur.name = f"ingest-{self.dataset}-{shard}"
            try:
                self._run_shard(shard, stop)
            finally:
                cur.name = old_name
        else:
            t.start()

    def stop_ingestion(self, shard: int) -> None:
        import time as _time
        with self._lock:
            stop = self._stops.get(shard)
            t = self._threads.get(shard)
        if stop is not None:
            stop.set()
        # the stream registers shortly after thread start; wait for it so
        # teardown can wake a consumer blocked on an empty queue (otherwise
        # a zombie consumer would keep draining the shared stream)
        deadline = _time.monotonic() + 2.0
        stream = None
        while _time.monotonic() < deadline:
            with self._lock:
                stream = self._streams.get(shard)
            if stream is not None or t is None or not t.is_alive():
                break
            _time.sleep(0.01)
        if stream is not None:
            stream.teardown()
        if t is not None and t is not threading.current_thread() \
                and t.is_alive():
            t.join(timeout=5.0)
            if t.is_alive():
                # still draining a large backlog: leave it tracked so a
                # restart cannot spawn a second consumer on the same
                # stream; the thread's own finally runs _cleanup on exit
                return
        self._cleanup(shard)

    def _cleanup(self, shard: int) -> None:
        with self._lock:
            self._threads.pop(shard, None)
            self._stops.pop(shard, None)
            self._streams.pop(shard, None)

    def stop_all(self) -> None:
        with self._lock:
            shards = list(self._threads)
        for s in shards:
            self.stop_ingestion(s)

    def running_shards(self) -> list[int]:
        with self._lock:
            return sorted(s for s, t in self._threads.items() if t.is_alive())

    # ------------------------------------------------------------- internals

    def _run_shard(self, shard: int, stop: threading.Event) -> None:
        flush_sched = None
        try:
            # setup already ran synchronously in start_ingestion
            self.memstore.recover_index(self.dataset, shard)

            # checkpointed recovery: replay from the earliest checkpoint;
            # per-group watermarks skip already-persisted records
            resume_from, highest = self.memstore.prepare_recovery(
                self.dataset, shard)
            stream = self.stream_factory.create(self.dataset, shard,
                                                offset=resume_from)
            with self._lock:
                self._streams[shard] = stream
            if stop.is_set():
                # stopped between start and stream registration: ensure a
                # sentinel exists (close is idempotent-until-delivered),
                # then fall through to the loop so it gets consumed —
                # never leave a stale sentinel for the next consumer
                stream.teardown()
            sh = self.memstore.get_shard(self.dataset, shard)
            # single-writer-per-shard tripwire (reference: FiloSchedulers
            # assertThreadName on the ingest scheduler); installed always —
            # the check itself no-ops unless assertions are enabled, and
            # installing unconditionally avoids order dependence on when
            # enable_assertions() is called
            from filodb_tpu.utils.schedulers import ingest_check_for
            sh.ingest_sched_check = ingest_check_for(self.dataset, shard)

            recovering = resume_from is not None
            if recovering:
                self.event_sink(RecoveryInProgress(self.dataset, shard,
                                                   self.node, 0))
            else:
                self.event_sink(IngestionStarted(self.dataset, shard,
                                                 self.node))
            # pipelined time-boundary flushes ride the ingest loop
            # (reference: ingestStream interleaves createFlushTasks,
            # TimeSeriesMemStore.scala:106-129); encode+IO run on the
            # flush executor, never this thread
            from filodb_tpu.memstore.flush import FlushScheduler
            if sh.config.flush_interval_ms > 0:
                flush_sched = FlushScheduler(
                    sh, sh.config.flush_interval_ms,
                    parallelism=sh.config.flush_task_parallelism)
                # expose the live pipeline to the watermark ledger
                # (/admin/shards flush-queue depth/age, ISSUE 6)
                sh.flush_scheduler = flush_sched
            n_since_report = 0
            # the group head only advances on the ~2 s gossip sweeps, so
            # the promotion target is refreshed on the report cadence
            # below — recomputing it per replayed record would put a
            # replica scan + max() in the bulk catch-up hot loop
            target = self._promotion_target(shard, highest) \
                if recovering else 0
            # the loop runs until the stream ends: a finite source drains,
            # a live queue delivers the teardown sentinel.  No early exit —
            # dequeued elements are always ingested (at-least-once) and the
            # sentinel is always consumed (no stale sentinel for the next
            # consumer of a shared stream).
            for offset, container in stream.get():
                sh.ingest_container(container, offset)
                if flush_sched is not None:
                    flush_sched.note_ingested()
                if recovering:
                    n_since_report += 1
                    report_due = (n_since_report
                                  >= self.recovery_report_interval)
                    if report_due:
                        n_since_report = 0
                        target = self._promotion_target(shard, highest)
                    if offset >= target:
                        recovering = False
                        self.event_sink(IngestionStarted(self.dataset, shard,
                                                         self.node))
                    elif report_due:
                        lo = resume_from or 0
                        span = max(target - lo, 1)
                        pct = min(int(100 * (offset - lo) / span), 99)
                        self.event_sink(RecoveryInProgress(
                            self.dataset, shard, self.node, pct))
            if recovering:
                # drained before reaching the last checkpoint (short replay)
                self.event_sink(IngestionStarted(self.dataset, shard,
                                                 self.node))
            if stop.is_set():
                # stream drained in response to a stop/teardown: the shard
                # really is stopped.  A finite source draining on its own
                # (CSV load) leaves the shard ACTIVE and queryable.
                self.event_sink(IngestionStopped(self.dataset, shard,
                                                 node=self.node))
        except Exception as e:  # noqa: BLE001 — report, don't kill the node
            traceback.print_exc()
            self.event_sink(IngestionError(self.dataset, shard, str(e),
                                           node=self.node))
        finally:
            if flush_sched is not None:
                try:
                    # drain in-flight flush tasks only; buffered rows stay
                    # queryable and flush on the next boundary or via the
                    # explicit flush surface (matches the reference: stop
                    # does not force a flush)
                    flush_sched.close(flush_remaining=False)
                except Exception:  # noqa: BLE001 — never mask the cause
                    traceback.print_exc()
                finally:
                    flush_sched.shard.flush_scheduler = None
            self._cleanup(shard)

    def _promotion_target(self, shard: int, highest: int) -> int:
        """The offset a recovering replica must reach before promotion:
        the local checkpoint head, raised to the replica group's
        gossiped head when one is known (ISSUE 7)."""
        if self.group_head_fn is None:
            return highest
        try:
            return max(highest, int(self.group_head_fn(shard)))
        except Exception:  # noqa: BLE001 — gossip mid-shutdown
            return highest

    def flush_loop(self, shard: int, stop: threading.Event,
                   interval_s: float) -> None:
        """Optional periodic flush driver (reference: time-boundary flush
        scheduling, TimeSeriesShard.scala:804-846)."""
        while not stop.wait(interval_s):
            self.memstore.flush(self.dataset, shard)


class NodeCoordinator:
    """Per-node entry point: one IngestionCoordinator per dataset plus the
    query surface (reference: NodeCoordinatorActor creating
    IngestionActor + QueryActor per dataset)."""

    def __init__(self, node: str, memstore: TimeSeriesMemStore):
        self.node = node
        self.memstore = memstore
        self.ingestion: dict[str, IngestionCoordinator] = {}
        self.planners: dict[str, object] = {}

    def setup_dataset(self, dataset: str, schemas: Schemas,
                      stream_factory: IngestionStreamFactory,
                      config: Optional[StoreConfig] = None,
                      event_sink=None,
                      group_head_fn=None) -> IngestionCoordinator:
        ic = IngestionCoordinator(self.node, dataset, schemas, self.memstore,
                                  stream_factory, config, event_sink,
                                  group_head_fn=group_head_fn)
        self.ingestion[dataset] = ic
        return ic

    def resync(self, dataset: str, assigned_shards: Sequence[int]) -> None:
        self.ingestion[dataset].resync(assigned_shards)

    def shutdown(self) -> None:
        for ic in self.ingestion.values():
            ic.stop_all()
