"""ReplicaSet: THE replica routing policy, in one place.

Every dispatcher site that targets, retargets, hedges, or fails over a
leaf plan selects its replica through :meth:`ReplicaSet.pick` — never an
ad-hoc node list (lint-enforced by tests/test_sentinel_lint.py::
test_replica_routing_goes_through_pick).  Mirrors the reference's
ActiveShardMapper routing (reference: ShardMapper.activeShard +
HighAvailabilityPlanner's healthy-replica preference) generalized to
replica groups (ISSUE 7).

Ordering, healthiest first:

1. **status** — ``Active`` replicas serve; ``Recovery`` replicas are
   queryable ONLY when the group has no Active peer (a recovering copy
   is complete up to its watermark but behind the head — serving it
   while a caught-up peer exists would silently return stale windows);
   when nothing is queryable yet (cluster start), non-Down replicas
   serve best-effort, matching the single-copy planner's behavior.
2. **watermark lag** — gossiped ``group_head - replica watermark``,
   bucketed by ``lag_tolerance_rows`` so a few in-flight rows of jitter
   between healthy peers never flaps routing.
3. **latency** — the local node ranks first (no network hop), then
   PR 10's calibrated per-endpoint dispatch latency (observed p50).
4. node name, for a stable total order.

Elastic resharding (ISSUE 13) needs NO special casing here, by
construction: split children are ordinary replica groups in the
mapper's (grown) shard space, invisible to fan-out until the cutover
flips ``num_shards`` — after which ``pick`` routes them exactly like
any other shard, including the Recovery-serves-only-without-an-Active-
peer rule for a child whose in-stream promotion has not fired yet.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus


class ReplicaSet:
    """Routing view over one dataset's ShardMapper replica groups."""

    def __init__(self, mapper: ShardMapper,
                 local_node: Optional[str] = None,
                 latency_fn: Optional[Callable[[str], Optional[float]]] = None,
                 lag_tolerance_rows: int = 256):
        self.mapper = mapper
        self.local_node = local_node
        self.latency_fn = latency_fn
        self.lag_tolerance_rows = max(int(lag_tolerance_rows), 1)

    def _latency_s(self, node: str) -> float:
        if node == self.local_node:
            return 0.0
        if self.latency_fn is not None:
            lat = self.latency_fn(node)
            if lat is not None:
                return float(lat)
        return float("inf")  # uncalibrated remote: after calibrated ones

    def pick(self, shard: int, exclude: Sequence[str] = ()) -> list[str]:
        """Ordered candidate nodes for one leaf dispatch, healthiest
        first.  ``exclude`` removes already-tried (failover) or
        already-targeted (hedge retarget) replicas.  Empty = no replica
        may serve — the caller degrades or fails loudly.

        The Recovery gate is evaluated over the WHOLE group, not the
        post-exclude pool: while ANY Active peer exists (even one that
        is excluded, slow, or not-yet-demoted dead), a mid-replay
        Recovery copy must not serve — it would silently answer with
        windows missing everything between its replay watermark and
        the head.  Failing loudly for the short detection window beats
        silently-wrong results."""
        excluded = set(exclude)
        group = self.mapper.replicas(shard)
        reps = [r for r in group if r.node not in excluded]
        active = [r for r in reps if r.status is ShardStatus.ACTIVE]
        if active:
            pool = active
        elif any(r.status is ShardStatus.ACTIVE for r in group):
            return []   # the Active peers are excluded/unreachable:
            #             never silently fall back to a stale copy
        else:
            # no Active peer anywhere: Recovery serves; if nothing is
            # queryable at all, any non-terminal copy is the best
            # effort.  STOPPED is terminal here too: an operator-
            # stopped replica's ingest is halted (the fanout refuses to
            # deliver to it), so serving it would return silently stale
            # data with no partial-results warning
            pool = [r for r in reps if r.status is ShardStatus.RECOVERY] \
                or [r for r in reps
                    if r.status not in (ShardStatus.DOWN,
                                        ShardStatus.ERROR,
                                        ShardStatus.STOPPED)]
        head = self.mapper.group_head(shard)

        def key(r):
            if head < 0:
                lag_bucket = 0          # nobody gossips: all equal
            elif r.watermark < 0:
                # UNKNOWN watermark while peers are known: rank worst
                # in its status tier — a possibly-diverged copy must
                # not tie with the group head and win on latency
                lag_bucket = float("inf")
            else:
                lag_bucket = max(head - r.watermark, 0) \
                    // self.lag_tolerance_rows
            return (lag_bucket, self._latency_s(r.node), r.node)

        return [r.node for r in sorted(pool, key=key)]

    def alternate(self, shard: int,
                  exclude: Sequence[str] = ()) -> Optional[str]:
        """The healthiest replica OTHER than ``exclude`` — the hedge
        retarget and next-failover choice, still through pick()."""
        order = self.pick(shard, exclude=exclude)
        return order[0] if order else None
