"""Cluster shard management: assignment strategy, ShardManager, events.

Capability match for the reference's coordination layer (reference:
coordinator/src/main/scala/filodb.coordinator/ShardManager.scala:28 —
add/remove nodes, SetupDataset, start/stop shard commands, reassignment
rate limit, ShardEvent pub-sub; ShardAssignmentStrategy.scala:9,36 —
DefaultShardAssignmentStrategy spreads shards evenly and is idempotent;
ShardStatus.scala:54-94 lifecycle).  The reference runs this inside an
Akka cluster-singleton actor; here it is a plain thread-safe state
machine the server main drives — membership events arrive from the
transport layer (HTTP control plane / process manager), and subscribers
receive ShardEvents synchronously.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence

from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus


# ---------------------------------------------------------------------------
# Shard events (reference: ShardEvent hierarchy in ShardStatus.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardEvent:
    dataset: str
    shard: int


@dataclasses.dataclass(frozen=True)
class ShardAssignmentStarted(ShardEvent):
    node: str


@dataclasses.dataclass(frozen=True)
class IngestionStarted(ShardEvent):
    node: str


@dataclasses.dataclass(frozen=True)
class RecoveryInProgress(ShardEvent):
    node: str
    progress_pct: int


@dataclasses.dataclass(frozen=True)
class IngestionStopped(ShardEvent):
    pass


@dataclasses.dataclass(frozen=True)
class IngestionError(ShardEvent):
    error: str


@dataclasses.dataclass(frozen=True)
class ShardDown(ShardEvent):
    node: Optional[str]


_EVENT_STATUS = {
    ShardAssignmentStarted: ShardStatus.ASSIGNED,
    IngestionStarted: ShardStatus.ACTIVE,
    RecoveryInProgress: ShardStatus.RECOVERY,
    IngestionStopped: ShardStatus.STOPPED,
    IngestionError: ShardStatus.ERROR,
    ShardDown: ShardStatus.DOWN,
}


# ---------------------------------------------------------------------------
# Assignment strategy
# ---------------------------------------------------------------------------


class ShardAssignmentStrategy:
    def shard_assignments(self, node: str, dataset: str, mapper: ShardMapper,
                          min_num_nodes: int) -> list[int]:
        raise NotImplementedError


class DefaultShardAssignmentStrategy(ShardAssignmentStrategy):
    """Spread shards evenly: each node gets ceil(num_shards/min_num_nodes)
    at most, preferring unassigned shards; idempotent — a node that already
    holds its quota gets the same recommendation back (reference:
    DefaultShardAssignmentStrategy.scala:36)."""

    def shard_assignments(self, node, dataset, mapper, min_num_nodes) -> list[int]:
        quota = -(-mapper.num_shards // max(min_num_nodes, 1))  # ceil
        have = mapper.shards_for_node(node)
        if len(have) >= quota:
            return have
        unassigned = [s for s in range(mapper.num_shards)
                      if mapper.coord_for_shard(s) is None]
        return have + unassigned[:quota - len(have)]


# ---------------------------------------------------------------------------
# Dataset registration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DatasetInfo:
    name: str
    num_shards: int
    min_num_nodes: int
    mapper: ShardMapper


# ---------------------------------------------------------------------------
# ShardManager
# ---------------------------------------------------------------------------


class ShardManager:
    """Shard assignment state machine + event hub (reference:
    ShardManager.scala:28).  Thread-safe; all mutation under one lock."""

    def __init__(self, strategy: Optional[ShardAssignmentStrategy] = None,
                 reassignment_min_interval_ms: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.strategy = strategy or DefaultShardAssignmentStrategy()
        self.reassignment_min_interval_ms = reassignment_min_interval_ms
        self._clock = clock
        self._lock = threading.RLock()
        self._datasets: dict[str, DatasetInfo] = {}
        self._nodes: list[str] = []  # deterministic join order
        self._subscribers: list[Callable[[ShardEvent], None]] = []
        # (dataset, shard) -> monotonic ms of last reassignment, for the
        # rate limit (reference: shard-manager.reassignment-min-interval)
        self._last_reassign: dict[tuple[str, int], float] = {}

    # ----------------------------------------------------------- membership

    def add_node(self, node: str) -> dict[str, list[int]]:
        """Member-up: assign shards for every dataset (reference:
        addMember).  Returns dataset -> shards assigned to this node."""
        with self._lock:
            if node not in self._nodes:
                self._nodes.append(node)
            out = {}
            for info in self._datasets.values():
                out[info.name] = self._assign(node, info)
            return out

    def remove_node(self, node: str) -> dict[str, list[int]]:
        """Member-down: mark its shards Down, then try to reassign them to
        surviving nodes under the rate limit (reference: removeMember +
        reassignment)."""
        with self._lock:
            if node in self._nodes:
                self._nodes.remove(node)
            freed: dict[str, list[int]] = {}
            for info in self._datasets.values():
                shards = info.mapper.shards_for_node(node)
                for s in shards:
                    info.mapper.unassign(s)
                    info.mapper.update_status(s, ShardStatus.DOWN)
                    self._publish(ShardDown(info.name, s, node))
                freed[info.name] = shards
            # reassign freed shards across survivors
            for ds, shards in freed.items():
                self._reassign(self._datasets[ds], shards)
            return freed

    @property
    def nodes(self) -> list[str]:
        with self._lock:
            return list(self._nodes)

    # -------------------------------------------------------------- datasets

    def setup_dataset(self, name: str, num_shards: int,
                      min_num_nodes: int) -> DatasetInfo:
        """SetupDataset: register and assign across current nodes
        (reference: NodeClusterActor.SetupDataset -> ShardManager)."""
        with self._lock:
            if name in self._datasets:
                return self._datasets[name]
            info = DatasetInfo(name, num_shards, min_num_nodes,
                               ShardMapper(num_shards))
            self._datasets[name] = info
            for node in self._nodes:
                self._assign(node, info)
            return info

    def mapper(self, dataset: str) -> ShardMapper:
        return self._datasets[dataset].mapper

    def datasets(self) -> list[str]:
        with self._lock:
            return list(self._datasets)

    # ------------------------------------------------------ start/stop admin

    def start_shards(self, dataset: str, shards: Sequence[int],
                     node: str) -> list[int]:
        """Operator StartShards command (reference: ShardManager
        startShards)."""
        with self._lock:
            info = self._datasets[dataset]
            started = []
            for s in shards:
                if info.mapper.coord_for_shard(s) is None:
                    info.mapper.register_node([s], node)
                    self._publish(ShardAssignmentStarted(dataset, s, node))
                    started.append(s)
            return started

    def stop_shards(self, dataset: str, shards: Sequence[int]) -> list[int]:
        with self._lock:
            info = self._datasets[dataset]
            stopped = []
            for s in shards:
                if info.mapper.coord_for_shard(s) is not None:
                    info.mapper.update_status(s, ShardStatus.STOPPED)
                    self._publish(IngestionStopped(dataset, s))
                    stopped.append(s)
            return stopped

    # ------------------------------------------------------------ event hub

    def subscribe(self, fn: Callable[[ShardEvent], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def publish_event(self, event: ShardEvent) -> None:
        """Ingestion coordinators report progress through here; the mapper
        status tracks the event (reference: ShardManager.updateFromExternal
        + StatusActor relay)."""
        with self._lock:
            info = self._datasets.get(event.dataset)
            if info is not None:
                status = _EVENT_STATUS.get(type(event))
                if status is not None:
                    progress = getattr(event, "progress_pct", 0)
                    info.mapper.update_status(event.shard, status, progress)
            self._publish(event)

    def _publish(self, event: ShardEvent) -> None:
        for fn in list(self._subscribers):
            fn(event)

    # ------------------------------------------------------------ internals

    def _assign(self, node: str, info: DatasetInfo) -> list[int]:
        shards = self.strategy.shard_assignments(node, info.name, info.mapper,
                                                 info.min_num_nodes)
        fresh = [s for s in shards if info.mapper.coord_for_shard(s) != node]
        if fresh:
            info.mapper.register_node(fresh, node)
            for s in fresh:
                self._publish(ShardAssignmentStarted(info.name, s, node))
        return info.mapper.shards_for_node(node)

    def _reassign(self, info: DatasetInfo, shards: Sequence[int]) -> list[int]:
        """Move freed shards to surviving nodes, at most once per shard per
        rate-limit interval."""
        if not self._nodes:
            return []
        now_ms = self._clock() * 1000.0
        moved = []
        for s in shards:
            key = (info.name, s)
            last = self._last_reassign.get(key)
            if last is not None and \
                    now_ms - last < self.reassignment_min_interval_ms:
                continue  # too soon; stays Down until next membership event
            # least-loaded surviving node
            node = min(self._nodes,
                       key=lambda n: len(info.mapper.shards_for_node(n)))
            info.mapper.register_node([s], node)
            self._last_reassign[key] = now_ms
            self._publish(ShardAssignmentStarted(info.name, s, node))
            moved.append(s)
        return moved


# ---------------------------------------------------------------------------
# Failure detection
# ---------------------------------------------------------------------------


class FailureDetector:
    """Heartbeat-timeout failure detector driving ShardManager.remove_node
    (reference: Akka Cluster failure detector + NodeLifecycleStrategy —
    down nodes have their shards reassigned)."""

    def __init__(self, manager: ShardManager, timeout_ms: int = 10_000,
                 clock: Callable[[], float] = time.monotonic):
        self.manager = manager
        self.timeout_ms = timeout_ms
        self._clock = clock
        self._lock = threading.Lock()
        self._last_seen: dict[str, float] = {}

    def heartbeat(self, node: str) -> None:
        with self._lock:
            first = node not in self._last_seen
            self._last_seen[node] = self._clock()
        if first:
            self.manager.add_node(node)

    def check(self) -> list[str]:
        """Sweep for dead nodes; returns the nodes declared down."""
        now = self._clock()
        with self._lock:
            dead = [n for n, t in self._last_seen.items()
                    if (now - t) * 1000.0 > self.timeout_ms]
            for n in dead:
                del self._last_seen[n]
        for n in dead:
            self.manager.remove_node(n)
        return dead

    def alive(self) -> list[str]:
        with self._lock:
            return sorted(self._last_seen)
