"""Cluster shard management: assignment strategy, ShardManager, events.

Capability match for the reference's coordination layer (reference:
coordinator/src/main/scala/filodb.coordinator/ShardManager.scala:28 —
add/remove nodes, SetupDataset, start/stop shard commands, reassignment
rate limit, ShardEvent pub-sub; ShardAssignmentStrategy.scala:9,36 —
DefaultShardAssignmentStrategy spreads shards evenly and is idempotent;
ShardStatus.scala:54-94 lifecycle).  The reference runs this inside an
Akka cluster-singleton actor; here it is a plain thread-safe state
machine the server main drives — membership events arrive from the
transport layer (HTTP control plane / process manager), and subscribers
receive ShardEvents synchronously.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence

from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus


# ---------------------------------------------------------------------------
# Shard events (reference: ShardEvent hierarchy in ShardStatus.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardEvent:
    dataset: str
    shard: int


@dataclasses.dataclass(frozen=True)
class ShardAssignmentStarted(ShardEvent):
    node: str


@dataclasses.dataclass(frozen=True)
class IngestionStarted(ShardEvent):
    node: str


@dataclasses.dataclass(frozen=True)
class RecoveryInProgress(ShardEvent):
    node: str
    progress_pct: int


@dataclasses.dataclass(frozen=True)
class IngestionStopped(ShardEvent):
    # the node whose LOCAL ingestion stopped; None = operator/leader
    # stop (publish_event uses this to tell a handoff tail — ownership
    # already moved elsewhere — from a real stop of the current owner)
    node: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class IngestionError(ShardEvent):
    error: str
    node: Optional[str] = None  # the replica that failed (ISSUE 7)


@dataclasses.dataclass(frozen=True)
class ShardDown(ShardEvent):
    node: Optional[str]


_EVENT_STATUS = {
    ShardAssignmentStarted: ShardStatus.ASSIGNED,
    IngestionStarted: ShardStatus.ACTIVE,
    RecoveryInProgress: ShardStatus.RECOVERY,
    IngestionStopped: ShardStatus.STOPPED,
    IngestionError: ShardStatus.ERROR,
    ShardDown: ShardStatus.DOWN,
}


# ---------------------------------------------------------------------------
# Assignment strategy
# ---------------------------------------------------------------------------


class ShardAssignmentStrategy:
    def shard_assignments(self, node: str, dataset: str, mapper: ShardMapper,
                          min_num_nodes: int) -> list[int]:
        raise NotImplementedError


class DefaultShardAssignmentStrategy(ShardAssignmentStrategy):
    """Spread shard REPLICAS evenly: each node gets at most
    ceil(num_shards * rf / min_num_nodes), preferring the shards with
    the fewest live replicas (empty groups fill before degraded ones);
    a node never holds two copies of one shard.  Idempotent — a node
    that already holds its quota gets the same recommendation back
    (reference: DefaultShardAssignmentStrategy.scala:36)."""

    def shard_assignments(self, node, dataset, mapper, min_num_nodes) -> list[int]:
        rf = mapper.replication_factor
        quota = -(-mapper.num_shards * rf // max(min_num_nodes, 1))  # ceil
        have = mapper.shards_for_node(node)
        if len(have) >= quota:
            return have
        # shards still short of rf live replicas that this node does not
        # already hold a copy of, emptiest groups first (stable by id);
        # one live_replicas snapshot per shard keeps the filter, the
        # membership check, and the sort key consistent (and O(1) each).
        # In-flight split CHILDREN (ISSUE 13) are never auto-placed —
        # a child replica only makes sense on a node that can clone the
        # parent's local data, which the SplitController arranges.
        live = {s: mapper.live_replicas(s)
                for s in range(mapper.num_shards)}
        need = sorted(
            (s for s in range(mapper.num_shards)
             if len(live[s]) < rf
             and mapper.split_parent_of(s) is None
             and all(r.node != node for r in live[s])),
            key=lambda s: (len(live[s]), s))
        return have + need[:quota - len(have)]


# ---------------------------------------------------------------------------
# Dataset registration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DatasetInfo:
    name: str
    num_shards: int
    min_num_nodes: int
    mapper: ShardMapper
    replication_factor: int = 1
    # once-per-transition state for the degraded-placement warning
    degraded: bool = False


# ---------------------------------------------------------------------------
# ShardManager
# ---------------------------------------------------------------------------


class ShardManager:
    """Shard assignment state machine + event hub (reference:
    ShardManager.scala:28).  Thread-safe; all mutation under one lock."""

    def __init__(self, strategy: Optional[ShardAssignmentStrategy] = None,
                 reassignment_min_interval_ms: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.strategy = strategy or DefaultShardAssignmentStrategy()
        self.reassignment_min_interval_ms = reassignment_min_interval_ms
        self._clock = clock
        self._lock = threading.RLock()
        self._datasets: dict[str, DatasetInfo] = {}
        self._nodes: list[str] = []  # deterministic join order
        self._subscribers: list[Callable[[ShardEvent], None]] = []
        # (dataset, shard) -> monotonic ms of last reassignment, for the
        # rate limit (reference: shard-manager.reassignment-min-interval)
        self._last_reassign: dict[tuple[str, int], float] = {}

    # ----------------------------------------------------------- membership

    def add_node(self, node: str) -> dict[str, list[int]]:
        """Member-up: assign shards for every dataset (reference:
        addMember).  Returns dataset -> shards assigned to this node."""
        with self._lock:
            if node not in self._nodes:
                self._nodes.append(node)
            out = {}
            for info in self._datasets.values():
                out[info.name] = self._assign(node, info)
                self._warn_if_degraded(info)
            return out

    def remove_node(self, node: str) -> dict[str, list[int]]:
        """Member-down: demote the node's replicas to Down — publishing
        ``ShardDown`` per affected REPLICA so subscribers and the
        named-mapper health metrics see every lost copy.  A group that
        keeps >=1 live replica serves from the survivor and is NOT
        re-placed (replica stickiness: the dead copy waits to rejoin
        from its checkpoint; the degraded group is warned loudly);
        only FULLY-dead groups are reassigned to surviving nodes, under
        the rate limit, to restore availability (reference:
        removeMember + reassignment)."""
        with self._lock:
            if node in self._nodes:
                self._nodes.remove(node)
            freed: dict[str, list[int]] = {}
            for info in self._datasets.values():
                # EVERY replica the node holds demotes (Error included —
                # shards_for_node only lists live copies).  Sweep the
                # TOTAL shard space: in-flight split children's dead
                # copies must demote too or the promotion gate would
                # wait on a ghost forever (ISSUE 13)
                shards = [s for s in range(info.mapper.total_shards)
                          if info.mapper.state(s).replica(node)
                          is not None]
                for s in shards:
                    # per-replica demotion: the transition counter and
                    # replica gauge emit through the named-mapper path
                    info.mapper.update_status(s, ShardStatus.DOWN,
                                              node=node)
                    self._publish(ShardDown(info.name, s, node))
                freed[info.name] = shards
            # restore rf across survivors
            for ds, shards in freed.items():
                self._reassign(self._datasets[ds], shards)
            return freed

    @property
    def nodes(self) -> list[str]:
        with self._lock:
            return list(self._nodes)

    # -------------------------------------------------------------- datasets

    def setup_dataset(self, name: str, num_shards: int,
                      min_num_nodes: int,
                      replication_factor: int = 1) -> DatasetInfo:
        """SetupDataset: register and assign across current nodes
        (reference: NodeClusterActor.SetupDataset -> ShardManager).
        ``replication_factor`` > 1 places each shard on that many
        DISTINCT nodes (ISSUE 7)."""
        with self._lock:
            if name in self._datasets:
                return self._datasets[name]
            info = DatasetInfo(name, num_shards, min_num_nodes,
                               ShardMapper(
                                   num_shards, dataset=name,
                                   replication_factor=replication_factor),
                               replication_factor=replication_factor)
            self._datasets[name] = info  # filolint: disable=bounded-cache — keyed by operator-configured dataset names, structurally bounded
            for node in self._nodes:
                self._assign(node, info)
            self._warn_if_degraded(info)
            return info

    def mapper(self, dataset: str) -> ShardMapper:
        return self._datasets[dataset].mapper

    def datasets(self) -> list[str]:
        with self._lock:
            return list(self._datasets)

    # ------------------------------------------------------ start/stop admin

    def start_shards(self, dataset: str, shards: Sequence[int],
                     node: str) -> list[int]:
        """Operator StartShards command (reference: ShardManager
        startShards)."""
        with self._lock:
            info = self._datasets[dataset]
            started = []
            for s in shards:
                live = info.mapper.live_replicas(s)
                if any(r.node == node for r in live):
                    continue  # already holds a live copy
                if live and len(live) >= info.replication_factor:
                    continue  # group already at full strength
                info.mapper.register_node([s], node)
                self._publish(ShardAssignmentStarted(dataset, s, node))
                started.append(s)
            return started

    def stop_shards(self, dataset: str, shards: Sequence[int]) -> list[int]:
        with self._lock:
            info = self._datasets[dataset]
            stopped = []
            for s in shards:
                if info.mapper.replicas(s):
                    # operator stop applies to EVERY replica: the whole
                    # group stops serving, not just the primary copy
                    for r in list(info.mapper.replicas(s)):
                        info.mapper.update_status(s, ShardStatus.STOPPED,
                                                  node=r.node)
                    self._publish(IngestionStopped(dataset, s))
                    stopped.append(s)
            return stopped

    # ------------------------------------------------------------ event hub

    def subscribe(self, fn: Callable[[ShardEvent], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def publish_event(self, event: ShardEvent) -> None:
        """Ingestion coordinators report progress through here; the mapper
        status tracks the event against the REPORTING NODE's replica
        (reference: ShardManager.updateFromExternal + StatusActor
        relay)."""
        with self._lock:
            info = self._datasets.get(event.dataset)
            if info is not None \
                    and not 0 <= event.shard < info.mapper.total_shards:
                # a discarded split child's dying consumer reporting
                # after the abort truncated the shard space (ISSUE 13)
                info = None
            if info is not None:
                status = _EVENT_STATUS.get(type(event))
                node = getattr(event, "node", None)
                if isinstance(event, IngestionStopped) \
                        and event.node is not None \
                        and info.mapper.state(event.shard).replica(
                            event.node) is None:
                    # handoff tail: this node stopped its local ingest
                    # because ownership MOVED — the new holder's
                    # lifecycle governs the status now; marking STOPPED
                    # here would stick (gossip never resurrects
                    # operator stops) and blind this node's queries to
                    # the shard forever
                    status = None
                if status is not None:
                    progress = getattr(event, "progress_pct", 0)
                    info.mapper.update_status(event.shard, status, progress,
                                              node=node)
            self._publish(event)

    def _publish(self, event: ShardEvent) -> None:
        for fn in list(self._subscribers):
            fn(event)

    # ------------------------------------------------------------ internals

    def _assign(self, node: str, info: DatasetInfo) -> list[int]:
        shards = self.strategy.shard_assignments(node, info.name, info.mapper,
                                                 info.min_num_nodes)
        fresh = [s for s in shards
                 if all(r.node != node
                        for r in info.mapper.live_replicas(s))]
        if fresh:
            info.mapper.register_node(fresh, node)
            for s in fresh:
                self._publish(ShardAssignmentStarted(info.name, s, node))
        return info.mapper.shards_for_node(node)

    def _reassign(self, info: DatasetInfo, shards: Sequence[int]) -> list[int]:
        """Restore AVAILABILITY for fully-dead groups from the surviving
        nodes, at most once per shard per rate-limit interval.  A group
        that keeps >= 1 live replica is NOT reassigned: the survivor
        serves, and the dead copy stays sticky so the node can rejoin
        and replay from its own checkpoint instead of the cluster
        re-moving the whole shard on every blip (replica stickiness —
        the degraded group is warned loudly below).  A node never
        receives a shard it already holds a live copy of."""
        if not self._nodes:
            # losing the LAST node is the worst placement transition of
            # all — it must still fire the degraded warning
            self._warn_if_degraded(info)
            return []
        now_ms = self._clock() * 1000.0
        moved = []
        for s in shards:
            if info.mapper.split_parent_of(s) is not None:
                # a fully-dead in-flight split CHILD is not reassigned:
                # a fresh node has no parent data to clone from, and an
                # empty promoted child would silently serve holes.  The
                # SplitController aborts (losslessly) or waits for the
                # holder to rejoin instead.
                continue
            if info.mapper.live_replicas(s):
                continue  # a surviving replica still covers the shard
            key = (info.name, s)
            last = self._last_reassign.get(key)
            if last is not None and \
                    now_ms - last < self.reassignment_min_interval_ms:
                continue  # too soon; stays Down until next membership event
            # least-loaded surviving node (the group is fully dead per
            # the guard above, so every survivor is a legal holder)
            node = min(self._nodes,
                       key=lambda n: len(info.mapper.shards_for_node(n)))
            info.mapper.register_node([s], node)
            self._last_reassign[key] = now_ms  # filolint: disable=bounded-cache — keyed by configured dataset names, structurally bounded
            self._publish(ShardAssignmentStarted(info.name, s, node))
            moved.append(s)
        self._warn_if_degraded(info)
        return moved

    def _warn_if_degraded(self, info: DatasetInfo) -> None:
        """LOUD once-per-transition warning when placement cannot reach
        the replication factor (rf > live nodes, or groups left short
        after a failure) — a degraded group has less failure headroom
        than the operator configured."""
        short = [s for s in range(info.mapper.total_shards)
                 if len(info.mapper.live_replicas(s))
                 < info.replication_factor]
        was = info.degraded
        degraded = bool(short)
        info.degraded = degraded
        if degraded and not was:
            import logging
            logging.getLogger(__name__).warning(
                "dataset %s: %d/%d shard groups below replication-factor "
                "%d (nodes=%d) — degraded placement, reduced failure "
                "headroom (first short shards: %s)",
                info.name, len(short), info.num_shards,
                info.replication_factor, len(self._nodes), short[:8])
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("shard.degraded_placement", dataset=info.name,
                          short_groups=len(short),
                          replication_factor=info.replication_factor,
                          nodes=len(self._nodes))


# ---------------------------------------------------------------------------
# Failure detection
# ---------------------------------------------------------------------------


class FailureDetector:
    """Heartbeat-timeout failure detector driving ShardManager.remove_node
    (reference: Akka Cluster failure detector + NodeLifecycleStrategy —
    down nodes have their shards reassigned)."""

    def __init__(self, manager: ShardManager, timeout_ms: int = 10_000,
                 clock: Callable[[], float] = time.monotonic):
        self.manager = manager
        self.timeout_ms = timeout_ms
        self._clock = clock
        self._lock = threading.Lock()
        self._last_seen: dict[str, float] = {}

    def heartbeat(self, node: str) -> None:
        with self._lock:
            first = node not in self._last_seen
            self._last_seen[node] = self._clock()
        if first:
            self.manager.add_node(node)

    def check(self) -> list[str]:
        """Sweep for dead nodes; returns the nodes declared down."""
        now = self._clock()
        with self._lock:
            dead = [n for n, t in self._last_seen.items()
                    if (now - t) * 1000.0 > self.timeout_ms]
            for n in dead:
                del self._last_seen[n]
        for n in dead:
            self.manager.remove_node(n)
        return dead

    def alive(self) -> list[str]:
        with self._lock:
            return sorted(self._last_seen)

    def fresh_nodes(self) -> list[str]:
        """Nodes whose heartbeat is within the timeout — a READ-ONLY
        liveness view (``check`` both reads and acts); used for leader
        computation so non-leaders never mutate membership."""
        now = self._clock()
        with self._lock:
            return sorted(n for n, t in self._last_seen.items()
                          if (now - t) * 1000.0 <= self.timeout_ms)


# ---------------------------------------------------------------------------
# Cross-node status propagation
# ---------------------------------------------------------------------------


class StatusPoller:
    """Propagates cluster state between nodes by polling peer
    ``/__health`` endpoints — the stand-in for the reference's cluster
    singleton + gossip (NodeClusterActor is a cluster singleton whose
    ShardMapper snapshots are pushed to every node; StatusActor relays
    shard events to it).

    Leadership is DYNAMIC: the lowest node name among the local node and
    the peers with fresh heartbeats acts as the singleton.  Only the
    acting leader runs failure detection and reassignment (one decider —
    no split-brain reassignment races); non-leaders adopt the leader's
    assignment view wholesale from its health payload.  If the leader
    dies, its heartbeat goes stale everywhere, the next-lowest live node
    becomes the acting leader, declares it down, and reassigns.

    Per-shard LIVENESS is per-node ground truth regardless of role: each
    node reports the shards its ingestion coordinator actually runs, and
    owners not running an assigned shard show as ASSIGNED (not ACTIVE),
    keeping queries off dead shards.  Operator STOPPED/DOWN statuses are
    sticky — gossip never resurrects them (stop-command propagation to
    the owning node's coordinator goes through the admin HTTP surface).

    A successful poll (even a 503 "unhealthy" one) heartbeats the peer
    into the FailureDetector.  The ``on_assignment_change`` hook
    (typically IngestionCoordinator.resync) runs on a dedicated thread —
    a slow resync (stop_ingestion joins) must never stall polling past
    the failure-detector timeout.

    Note: ClusterBootstrap (coordinator/bootstrap.py) also probes
    ``/__health``, but only for seed discovery at join time; this poller
    owns the steady-state gossip.  Run one or the other's background
    loop, not both.
    """

    def __init__(self, manager: ShardManager, failure_detector: FailureDetector,
                 peers: dict[str, str], local_node: str,
                 interval_s: float = 2.0, timeout_s: float = 2.0,
                 on_assignment_change: Optional[Callable[[], None]] = None,
                 local_running: Optional[Callable[[str], list]] = None,
                 local_watermarks: Optional[
                     Callable[[str], dict]] = None,
                 tier_watermarks=None):
        from concurrent.futures import ThreadPoolExecutor

        self.manager = manager
        self.detector = failure_detector
        self.peers = dict(peers)
        self.local_node = local_node
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.on_assignment_change = on_assignment_change
        # dataset -> shards the LOCAL coordinator actually runs; when set,
        # every sweep self-heals: an assigned-but-not-running local shard
        # (its ingest thread died) triggers the assignment-change hook,
        # whose resync restarts it
        self.local_running = local_running
        # dataset -> {shard: ingested offset} for the LOCAL node; folded
        # into the mapper's replica watermarks each sweep so group_head
        # (the recovery-promotion gate, ISSUE 7) sees this node too
        self.local_watermarks = local_watermarks
        # rollup tier closure gossip (ROADMAP 2b): peers' /__health
        # "rollup" payloads fold into this TierWatermarks store so the
        # resolution router can stitch at the cluster-wide boundary
        self.tier_watermarks = tier_watermarks
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(len(self.peers), 8)),
            thread_name_prefix="status-poll")
        # async hook runner: coalesces bursts into one pending resync.
        # _hook_alive flips only under _hook_lock, atomically with the
        # final pending check, so a signal can never land between "thread
        # decided to exit" and "thread observed dead" and get dropped
        self._change_pending = threading.Event()  # guarded-by: _hook_lock
        self._hook_thread: Optional[threading.Thread] = None
        self._hook_lock = threading.Lock()
        self._hook_alive = False  # guarded-by: _hook_lock

    @property
    def leader(self) -> str:
        """The acting singleton: lowest name among self + fresh peers."""
        fresh = set(self.detector.fresh_nodes())
        candidates = [self.local_node] + [p for p in self.peers
                                          if p in fresh]
        return min(candidates)

    def _fetch_health(self, endpoint: str):
        import json as _json
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(f"{endpoint}/__health",
                                        timeout=self.timeout_s) as r:
                return _json.loads(r.read())
        except urllib.error.HTTPError as e:
            # a 503 "unhealthy" answer is still a live peer — its own
            # view may lag the leader's; the body still carries the
            # running-shards ground truth
            try:
                return _json.loads(e.read())
            except Exception:  # noqa: BLE001
                return None
        except Exception:  # noqa: BLE001 — unreachable peer: no beat
            return None

    def poll_once(self) -> list[str]:
        """One sweep: poll peers concurrently, adopt the acting leader's
        assignment view, apply liveness; the acting leader additionally
        runs failure detection + reassignment.  Returns nodes this sweep
        declared down (always [] on non-leaders)."""
        # the local node is trivially alive: never let its own heartbeat
        # lapse into a self-down declaration
        self.detector.heartbeat(self.local_node)
        self._note_local_watermarks()
        targets = [(p, ep) for p, ep in self.peers.items()
                   if p != self.local_node]
        bodies = list(self._pool.map(
            lambda t: (t[0], self._fetch_health(t[1])), targets))             if targets else []
        changed = False
        for peer, body in bodies:
            if body is None:
                continue
            self.detector.heartbeat(peer)
            # topology adoption (ISSUE 13) runs FIRST and from ANY peer:
            # generations are strictly monotone, so newest-wins is safe
            # regardless of leadership, and the grown shard space must
            # exist before this sweep's replica rows can land on it
            changed |= self._adopt_topology(body)
            leader = self.leader
            if peer == leader and leader != self.local_node:
                changed |= self._adopt_leader_view(body)
            self._apply_liveness(peer, body)
            if self.tier_watermarks is not None:
                for ds, tiers in (body.get("rollup") or {}).items():
                    self.tier_watermarks.note(peer, ds, tiers)
        down: list[str] = []
        if self.leader == self.local_node:
            # one decider: only the acting leader mutates membership
            down = self.detector.check()
        if self.tier_watermarks is not None:
            for peer in down:
                # a dead owner's frozen closure must not cap the
                # cluster boundary after its shards reassign
                self.tier_watermarks.forget(peer)
        if down or changed or self._local_needs_heal():
            self._signal_change()
        return down

    def _note_local_watermarks(self) -> None:
        """Fold the local node's ingested offsets into its replica rows
        so ``group_head`` reflects this node without a network hop."""
        if self.local_watermarks is None:
            return
        for ds in self.manager.datasets():
            mapper = self.manager.mapper(ds)
            try:
                wms = self.local_watermarks(ds) or {}
            except Exception:  # noqa: BLE001 — store mid-shutdown
                continue
            with self.manager._lock:  # mapper mutation under the
                # manager lock: a concurrent register_node/set_replicas
                # replaces the replica list, and a watermark written to
                # a discarded ReplicaState would be silently lost
                for shard, offset in wms.items():
                    mapper.note_watermark(int(shard), self.local_node,
                                          int(offset))

    def _local_needs_heal(self) -> bool:
        """True when a locally-assigned shard is not actually running
        (its ingest thread died) — the resync hook restarts it; without
        this the shard would stay ASSIGNED (unqueryable) forever."""
        if self.local_running is None:
            return False
        for ds in self.manager.datasets():
            mapper = self.manager.mapper(ds)
            assigned = set(mapper.runnable_shards_for_node(self.local_node))
            if assigned - set(self.local_running(ds)):
                return True
        return False

    def _signal_change(self) -> None:
        if self.on_assignment_change is None:
            return
        with self._hook_lock:
            self._change_pending.set()
            if not self._hook_alive:
                self._hook_alive = True
                self._hook_thread = threading.Thread(
                    target=self._run_hook, name="assignment-change",
                    daemon=True)
                self._hook_thread.start()

    def _run_hook(self) -> None:
        import traceback as _tb
        while True:
            with self._hook_lock:
                if self._stop.is_set() or not self._change_pending.is_set():
                    self._hook_alive = False
                    return
                self._change_pending.clear()
            try:
                self.on_assignment_change()
            except Exception:  # noqa: BLE001 — report, keep gossiping
                _tb.print_exc()

    def _adopt_topology(self, body: dict) -> bool:
        """Fold a peer's gossiped per-dataset topology (shard counts,
        generation, split phase) into the local mappers — the cluster-
        wide propagation path for live shard splits (ISSUE 13).  The
        SplitController on the triggering node drives the transitions;
        everyone else converges here within one poll interval."""
        changed = False
        topo = body.get("topology") or {}
        if not topo:
            return False
        with self.manager._lock:
            for ds, payload in topo.items():
                if ds not in self.manager.datasets():
                    continue
                changed |= self.manager.mapper(ds).adopt_topology(payload)
        return changed

    def _adopt_leader_view(self, body: dict) -> bool:
        """Replace local shard OWNERSHIP (the full replica group) with
        the leader's (reference: every node caches the singleton's
        ShardMapper snapshots).  Returns True when any membership
        changed."""
        changed = False
        with self.manager._lock:  # mapper mutation under the manager lock
            for ds, shards in (body.get("shards") or {}).items():
                if ds not in self.manager.datasets():
                    continue
                mapper = self.manager.mapper(ds)
                for st in shards:
                    shard = int(st.get("shard", -1))
                    # total_shards: in-flight split children's replica
                    # groups gossip like any other (ISSUE 13)
                    if not 0 <= shard < mapper.total_shards:
                        continue
                    rows = st.get("replicas")
                    if rows is None:
                        # legacy single-copy payload shape
                        node = st.get("node")
                        rows = [] if node is None else [
                            {"node": node, "status": st.get("status")}]
                    changed |= mapper.set_replicas(shard, rows)
        return changed

    def _apply_liveness(self, peer: str, body: dict) -> None:
        """Peer-reported running shards are ground truth for liveness of
        the REPLICAS we think the peer holds; membership is not touched
        and operator STOPPED/DOWN statuses are never overwritten.  The
        peer's per-shard ingested offsets feed its replica watermarks
        (the group-head promotion gate, ISSUE 7)."""
        running = body.get("running") or {}
        watermarks = body.get("watermarks") or {}
        peer_status: dict[tuple[str, int], str] = {}
        peer_progress: dict[tuple[str, int], int] = {}
        for ds, shards in (body.get("shards") or {}).items():
            for st in shards:
                shard = int(st.get("shard", -1))
                status = st.get("status")
                for rep in st.get("replicas") or ():
                    if rep.get("node") == peer:
                        status = rep.get("status")
                        peer_progress[(ds, shard)] = \
                            int(rep.get("progress") or 0)
                peer_status[(ds, shard)] = status
        with self.manager._lock:
            for ds in self.manager.datasets():
                mapper = self.manager.mapper(ds)
                live = {int(s) for s in running[ds]} if ds in running \
                    else None
                ds_wms = watermarks.get(ds) or {}
                for shard in range(mapper.total_shards):
                    rep = mapper.state(shard).replica(peer)
                    if rep is None:
                        continue
                    if str(shard) in ds_wms or shard in ds_wms:
                        off = ds_wms.get(str(shard), ds_wms.get(shard))
                        mapper.note_watermark(shard, peer, int(off))
                    if rep.status in (ShardStatus.STOPPED, ShardStatus.DOWN):
                        continue  # operator/leader intent is sticky
                    if live is None:
                        # no running info: fall back to the peer's own
                        # reported status + progress (defaulting
                        # progress would wipe a recovering replica's
                        # percentage to 0 on every sweep)
                        try:
                            mapper.update_status(
                                shard,
                                ShardStatus(peer_status.get((ds, shard))),
                                progress=peer_progress.get(
                                    (ds, shard), rep.recovery_progress),
                                node=peer)
                        except ValueError:
                            pass
                        continue

                    if shard in live:
                        # peer runs it; honor its RECOVERY sub-state.
                        # Progress comes from the peer's OWN gossiped
                        # row when present — the owner's recovery
                        # events never reach this node's ShardManager,
                        # and register_node reset the local copy to 0
                        # at rejoin, so the local value shows a replica
                        # stuck at 0% for the whole replay
                        reported = peer_status.get((ds, shard))
                        status = ShardStatus.RECOVERY \
                            if reported == ShardStatus.RECOVERY.value \
                            else ShardStatus.ACTIVE
                        keep = peer_progress.get(
                            (ds, shard), rep.recovery_progress) \
                            if status is ShardStatus.RECOVERY else 0
                        mapper.update_status(shard, status, progress=keep,
                                             node=peer)
                    else:
                        mapper.update_status(shard, ShardStatus.ASSIGNED,
                                             node=peer)

    def start(self) -> None:
        def loop():
            import traceback as _tb
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — keep polling, loudly
                    _tb.print_exc()

        self._thread = threading.Thread(target=loop, name="status-poller",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # pending/alive mutate only under _hook_lock (the invariant the
        # class header documents); the unlocked clear here could race a
        # concurrent _signal_change's locked set.  The lock is released
        # before the joins below — _run_hook needs it to exit.
        with self._hook_lock:
            self._change_pending.clear()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._hook_thread is not None:
            self._hook_thread.join(timeout=5)
        self._pool.shutdown(wait=False)
