"""Cluster coordination: query planners, shard management, server plumbing
(reference: coordinator/src/main/scala/filodb.coordinator/)."""
