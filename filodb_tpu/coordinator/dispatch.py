"""Cross-node plan dispatch over HTTP.

Capability match for the reference's ActorPlanDispatcher (reference:
exec/PlanDispatcher.scala:29-46 — Akka ask of a Kryo-serialized ExecPlan
to the shard's owning node; remote QueryActor executes and replies with
a QueryResult; SURVEY.md §3.1 'PROCESS BOUNDARY').  Here the transport
is HTTP POST /execplan with the JSON wire format
(filodb_tpu/query/wire.py); the receiving node executes against its own
memstore and returns the serialized result.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Callable, Optional

from filodb_tpu.query.exec import ExecContext, PlanDispatcher
from filodb_tpu.query.model import QueryError, QueryResult
from filodb_tpu.query.wire import (deserialize_plan, deserialize_result,
                                   serialize_plan, serialize_result)


class HttpPlanDispatcher(PlanDispatcher):
    """Ships a leaf plan to ``endpoint`` and returns its result."""

    def __init__(self, endpoint: str, timeout_s: float = 60.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def dispatch(self, plan, ctx: ExecContext) -> QueryResult:
        body = json.dumps(serialize_plan(plan)).encode()
        req = urllib.request.Request(
            f"{self.endpoint}/execplan", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read()).get("error", "")
            except Exception:
                err = f"HTTP {e.code}"
            raise QueryError(plan.query_context.query_id,
                             f"remote dispatch to {self.endpoint} failed: "
                             f"{err}") from e
        return deserialize_result(payload)

    def __repr__(self) -> str:
        return f"HttpPlanDispatcher({self.endpoint})"


def execplan_handler(memstore) -> Callable[[dict], dict]:
    """Server side: wire dict -> execute locally -> wire result.
    Transformers run here too (shard-local map/window work stays on the
    data node, as in the reference's remote QueryActor)."""

    def handle(payload: dict) -> dict:
        plan = deserialize_plan(payload)
        ctx = ExecContext(memstore, plan.query_context)
        result = plan.execute(ctx)
        return serialize_result(result)

    return handle


def dispatcher_factory(mapper, endpoints: dict[str, str],
                       local_node: Optional[str] = None
                       ) -> Callable[[int], PlanDispatcher]:
    """shard -> dispatcher, from the ShardMapper's owner and a node ->
    endpoint map (the plug for SingleClusterPlanner.dispatcher_for_shard).
    Shards owned by ``local_node`` (or by unknown nodes) execute
    in-process."""
    from filodb_tpu.query.exec import IN_PROCESS

    cache: dict[str, HttpPlanDispatcher] = {}

    def for_shard(shard: int) -> PlanDispatcher:
        node = mapper.coord_for_shard(shard)
        if node is None or node == local_node:
            return IN_PROCESS
        endpoint = endpoints.get(node)
        if endpoint is None:
            # a remote-owned shard with no known endpoint must FAIL the
            # query, not silently scan an empty local store
            return _UnroutableDispatcher(shard, node)
        d = cache.get(node)
        if d is None:
            d = cache[node] = HttpPlanDispatcher(endpoint)
        return d

    return for_shard


class _UnroutableDispatcher(PlanDispatcher):
    def __init__(self, shard: int, node: str):
        self.shard = shard
        self.node = node

    def dispatch(self, plan, ctx) -> QueryResult:
        raise QueryError(
            plan.query_context.query_id,
            f"shard {self.shard} is owned by node {self.node!r} but no "
            f"endpoint is configured for it — refusing to return partial "
            f"results")
