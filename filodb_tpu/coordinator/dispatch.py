"""Cross-node plan dispatch over HTTP.

Capability match for the reference's ActorPlanDispatcher (reference:
exec/PlanDispatcher.scala:29-46 — Akka ask of a Kryo-serialized ExecPlan
to the shard's owning node; remote QueryActor executes and replies with
a QueryResult; SURVEY.md §3.1 'PROCESS BOUNDARY').  Here the transport
is HTTP POST /execplan with the JSON wire format
(filodb_tpu/query/wire.py); the receiving node executes against its own
memstore and returns the serialized result.
"""

from __future__ import annotations

import collections
import concurrent.futures
import json
import threading
import time
import urllib.request
from typing import Callable, Optional

from filodb_tpu.query.exec import ExecContext, PlanDispatcher
from filodb_tpu.query.model import QueryError, QueryResult, ShardUnavailable
from filodb_tpu.query.wire import (deserialize_plan, deserialize_result,
                                   serialize_plan, serialize_result)
from filodb_tpu.utils.observability import TRACER
from filodb_tpu.workload import deadline as dl

TRACE_HEADER = "X-FiloDB-Trace-Id"
PARENT_SPAN_HEADER = "X-FiloDB-Parent-Span"

_WM = None


def _wm() -> dict:
    """The filodb_dispatch_* metric objects, resolved once per process
    (no registry-lock lookups on the dispatch hot path)."""
    global _WM
    if _WM is None:
        from filodb_tpu.utils.observability import workload_metrics
        _WM = workload_metrics()
    return _WM


_HEDGE_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None
_HEDGE_POOL_LOCK = threading.Lock()


def _hedge_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _HEDGE_POOL
    with _HEDGE_POOL_LOCK:
        if _HEDGE_POOL is None:
            _HEDGE_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="dispatch-hedge")
        return _HEDGE_POOL


class HttpPlanDispatcher(PlanDispatcher):
    """Ships a leaf plan to ``endpoint`` and returns its result.

    Trace context crosses the process boundary twice over: the
    ``trace_id`` rides the execplan wire dict (QueryContext field) AND
    the HTTP headers; the data node returns its spans with the result
    so the coordinator's TraceStore holds ONE stitched tree.

    Workload hardening (ISSUE 5):

    - every attempt's socket timeout is ``min(timeout_s cap, remaining
      deadline budget)`` — never a fixed constant (satellite #1 fix);
    - CONNECTION-level failures (refused/reset/DNS/socket timeout)
      retry up to ``max_retries`` times with exponential backoff, budget
      permitting; an HTTP response is never retried (the server spoke —
      re-asking multiplies load exactly when it must not);
    - with ``hedge=True`` a tail-slow first attempt triggers ONE hedged
      duplicate once it exceeds the dispatcher's observed p99 latency
      (read-only /execplan work is idempotent); first success wins;
    - a dispatch that exhausts retries raises :class:`ShardUnavailable`
      so scatter-gather can degrade to a warned partial result when the
      query allows it."""

    def __init__(self, endpoint: str, timeout_s: float = 60.0,
                 max_retries: int = 2, backoff_s: float = 0.05,
                 hedge: bool = False, hedge_min_s: float = 0.05,
                 hedge_warmup: int = 16):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s
        self.max_retries = max(int(max_retries), 0)
        self.backoff_s = float(backoff_s)
        self.hedge = bool(hedge)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_warmup = max(int(hedge_warmup), 1)
        # recent successful-attempt latencies -> p99 hedge trigger
        self._lat: collections.deque = collections.deque(maxlen=128)
        self._lat_lock = threading.Lock()

    # -------------------------------------------------------------- transport

    def _note_latency(self, seconds: float) -> None:
        with self._lat_lock:
            self._lat.append(seconds)

    def hedge_delay_s(self) -> Optional[float]:
        """p99 of recent attempt latencies (floored at ``hedge_min_s``);
        None until ``hedge_warmup`` samples exist — hedging stays off
        until the trigger is data-driven."""
        with self._lat_lock:
            lat = sorted(self._lat)
        if len(lat) < self.hedge_warmup:
            return None
        return max(lat[min(int(0.99 * len(lat)), len(lat) - 1)],
                   self.hedge_min_s)

    def _send_once(self, body: bytes, headers: dict,
                   deadline_timeout_s: float) -> dict:
        req = urllib.request.Request(
            f"{self.endpoint}/execplan", data=body, method="POST",
            headers=headers)
        t0 = time.perf_counter()
        with urllib.request.urlopen(req,
                                    timeout=deadline_timeout_s) as resp:
            payload = json.loads(resp.read())
        self._note_latency(time.perf_counter() - t0)
        return payload

    def _send_hedged(self, make_body, headers: dict,
                     deadline_timeout_s: float) -> dict:
        """First attempt with a p99-armed hedge: when the primary is
        still in flight past the hedge delay, launch ONE duplicate and
        take whichever answers first.  The WHOLE hedged attempt —
        hedge-delay wait included — stays inside ``deadline_timeout_s``
        so a tail-latency storm cannot pin dispatch threads past the
        deadline they exist to enforce."""
        t_start = time.perf_counter()
        delay = self.hedge_delay_s()
        if delay is None or delay >= deadline_timeout_s:
            return self._send_once(make_body(), headers,
                                   deadline_timeout_s)
        pool = _hedge_pool()
        first = pool.submit(self._send_once, make_body(), headers,
                            deadline_timeout_s)
        try:
            return first.result(timeout=delay)
        except concurrent.futures.TimeoutError:
            pass  # tail-slow: hedge below
        m = _wm()
        m["dispatch_hedged"].inc(endpoint=self.endpoint)
        # fresh body: the wire budget_ms re-encodes from what is left NOW
        second = pool.submit(self._send_once, make_body(), headers,
                             deadline_timeout_s)
        pending = {first: "first", second: "second"}
        last_err: Optional[BaseException] = None
        while pending:
            budget_left = deadline_timeout_s \
                - (time.perf_counter() - t_start)
            if budget_left <= 0:
                break
            done, _ = concurrent.futures.wait(
                set(pending), timeout=budget_left,
                return_when=concurrent.futures.FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                tag = pending.pop(fut)
                err = fut.exception()
                if err is None:
                    if tag == "second":
                        m["dispatch_hedge_wins"].inc(
                            endpoint=self.endpoint)
                    return fut.result()
                last_err = err
        raise last_err if last_err is not None else TimeoutError(
            f"hedged dispatch to {self.endpoint} timed out")

    def _request(self, plan, make_body, headers: dict) -> dict:
        """Deadline-capped attempt loop: bounded retry-with-backoff on
        connection errors, optional p99 hedging on the first attempt.
        ``make_body`` re-serializes the plan PER ATTEMPT: the wire's
        relative ``budget_ms`` must reflect what is left NOW, not what
        was left before a failed attempt burned part of it — a stale
        body would let the data node re-anchor budget the coordinator
        already spent."""
        qctx = plan.query_context
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            rem = dl.remaining_ms(qctx)
            if rem is not None and rem <= 0:
                if last_err is None:
                    raise dl.DeadlineExceeded(
                        qctx.query_id,
                        f"deadline exhausted before dispatch to "
                        f"{self.endpoint}")
                break  # budget gone mid-retry: report the transport error
            deadline_timeout_s = dl.budget_timeout_s(qctx, self.timeout_s)
            try:
                if attempt == 0 and self.hedge:
                    return self._send_hedged(make_body, headers,
                                             deadline_timeout_s)
                return self._send_once(make_body(), headers,
                                       deadline_timeout_s)
            except urllib.error.HTTPError:
                raise  # the server answered: never retry (load-safe)
            except (urllib.error.URLError, OSError) as e:
                last_err = e
                if attempt < self.max_retries:
                    _wm()["dispatch_retries"].inc(endpoint=self.endpoint)
                    pause = self.backoff_s * (2 ** attempt)
                    rem = dl.remaining_ms(qctx)
                    if rem is not None:
                        pause = min(pause, max(rem / 1000.0, 0.0))
                    if pause > 0:
                        time.sleep(pause)
        _wm()["dispatch_failures"].inc(endpoint=self.endpoint)
        raise ShardUnavailable(
            qctx.query_id,
            f"remote dispatch to {self.endpoint} failed after "
            f"{self.max_retries + 1} attempt(s): {last_err}") from last_err

    # --------------------------------------------------------------- dispatch

    def dispatch(self, plan, ctx: ExecContext) -> QueryResult:
        tid = plan.query_context.trace_id or ctx.query_context.trace_id \
            or TRACER.current_trace_id()
        if tid and not plan.query_context.trace_id:
            plan.query_context.trace_id = tid
        with TRACER.span("dispatch.http", endpoint=self.endpoint,
                         plan=type(plan).__name__,
                         shard=getattr(plan, "shard", "")) as sp:
            # serialized per attempt (see _request): the wire budget_ms
            # is encoded at build time; all builds land in the
            # serialize timing bucket
            ser_box = [0.0]

            def make_body():
                t0 = time.perf_counter()
                body = json.dumps(serialize_plan(plan)).encode()
                ser_box[0] += time.perf_counter() - t0
                return body

            headers = {"Content-Type": "application/json"}
            if tid:
                headers[TRACE_HEADER] = tid
                headers[PARENT_SPAN_HEADER] = sp.span_id
            try:
                payload = self._request(plan, make_body, headers)
            except urllib.error.HTTPError as e:
                try:
                    err = json.loads(e.read()).get("error", "")
                except Exception:
                    err = f"HTTP {e.code}"
                if e.code == 503:
                    # the data node REFUSED the work (overload / budget
                    # too small to finish): transport-class failure, so
                    # allow_partial_results can degrade it
                    raise ShardUnavailable(
                        plan.query_context.query_id,
                        f"remote dispatch to {self.endpoint} refused: "
                        f"{err}") from e
                raise QueryError(plan.query_context.query_id,
                                 f"remote dispatch to {self.endpoint} "
                                 f"failed: {err}") from e
            t1 = time.perf_counter()
            spans = payload.get("spans") if isinstance(payload, dict) else None
            if tid and spans:
                try:
                    from filodb_tpu.utils.forensics import TRACE_STORE
                    TRACE_STORE.ingest_remote(tid, spans)
                except Exception:  # noqa: BLE001 — stitching is best-effort
                    pass
            result = deserialize_result(payload)
            ctx.note_timing("serialize",
                            ser_box[0] + (time.perf_counter() - t1))
            # remote stats fold into the coordinator's accounting exactly
            # like local leaves noting into the shared ctx
            ctx.absorb_stats(result.stats)
            return result

    def __repr__(self) -> str:
        return f"HttpPlanDispatcher({self.endpoint})"


def execplan_handler(memstore) -> Callable[..., dict]:
    """Server side: wire dict -> execute locally -> wire result.
    Transformers run here too (shard-local map/window work stays on the
    data node, as in the reference's remote QueryActor).  The originating
    query's trace context (wire field, or the HTTP headers passed as
    ``trace_parent``) is attached so this node's spans join the tree;
    they are shipped back under the ``spans`` key of the response."""

    def handle(payload: dict,
               trace_parent: Optional[tuple] = None) -> dict:
        plan = deserialize_plan(payload)
        tid = plan.query_context.trace_id or \
            (trace_parent[0] if trace_parent else None)
        # parent ONLY onto the caller's span id: any span still open on
        # this node (e.g. the leaf scheduler's run span enclosing this
        # handler) closes after the response's span list is built, so
        # parenting under it would orphan the whole remote subtree on
        # the coordinator in a real multi-process deployment
        parent_sid = trace_parent[1] if trace_parent else None
        ctx = ExecContext(memstore, plan.query_context)
        if not tid:
            return serialize_result(plan.execute(ctx))
        from filodb_tpu.utils.forensics import TRACE_STORE, span_to_dict
        with TRACER.attach((tid, parent_sid)):
            result = plan.execute(ctx)
        out = serialize_result(result)
        try:
            out["spans"] = [span_to_dict(r)
                            for r in TRACE_STORE.spans_for(tid)]
        except Exception:  # noqa: BLE001 — span return is best-effort
            pass
        return out

    return handle


def dispatcher_factory(mapper, endpoints: dict[str, str],
                       local_node: Optional[str] = None,
                       dispatch_config: Optional[dict] = None
                       ) -> Callable[[int], PlanDispatcher]:
    """shard -> dispatcher, from the ShardMapper's owner and a node ->
    endpoint map (the plug for SingleClusterPlanner.dispatcher_for_shard).
    Shards owned by ``local_node`` (or by unknown nodes) execute
    in-process.  ``dispatch_config`` (the standalone ``workload.
    dispatch`` block) tunes the timeout cap / retries / hedging of the
    HTTP dispatchers it builds."""
    from filodb_tpu.query.exec import IN_PROCESS

    cfg = dispatch_config or {}
    kwargs = dict(
        timeout_s=float(cfg.get("timeout-cap-s", 60.0)),
        max_retries=int(cfg.get("retries", 2)),
        backoff_s=float(cfg.get("backoff-s", 0.05)),
        hedge=bool(cfg.get("hedge", False)),
        hedge_min_s=float(cfg.get("hedge-min-s", 0.05)))
    cache: dict[str, HttpPlanDispatcher] = {}

    def for_shard(shard: int) -> PlanDispatcher:
        node = mapper.coord_for_shard(shard)
        if node is None or node == local_node:
            return IN_PROCESS
        endpoint = endpoints.get(node)
        if endpoint is None:
            # a remote-owned shard with no known endpoint must FAIL the
            # query (or degrade to a warned partial result when the
            # query opts in), never silently scan an empty local store
            return _UnroutableDispatcher(shard, node)
        d = cache.get(node)
        if d is None:
            d = cache[node] = HttpPlanDispatcher(endpoint, **kwargs)
        return d

    return for_shard


class _UnroutableDispatcher(PlanDispatcher):
    def __init__(self, shard: int, node: str):
        self.shard = shard
        self.node = node

    def dispatch(self, plan, ctx) -> QueryResult:
        raise ShardUnavailable(
            plan.query_context.query_id,
            f"shard {self.shard} is owned by node {self.node!r} but no "
            f"endpoint is configured for it — refusing to serve it from "
            f"the local store")
