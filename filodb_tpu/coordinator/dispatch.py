"""Cross-node plan dispatch over HTTP.

Capability match for the reference's ActorPlanDispatcher (reference:
exec/PlanDispatcher.scala:29-46 — Akka ask of a Kryo-serialized ExecPlan
to the shard's owning node; remote QueryActor executes and replies with
a QueryResult; SURVEY.md §3.1 'PROCESS BOUNDARY').  Here the transport
is HTTP POST /execplan with the JSON wire format
(filodb_tpu/query/wire.py); the receiving node executes against its own
memstore and returns the serialized result.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Callable, Optional

from filodb_tpu.query.exec import ExecContext, PlanDispatcher
from filodb_tpu.query.model import QueryError, QueryResult
from filodb_tpu.query.wire import (deserialize_plan, deserialize_result,
                                   serialize_plan, serialize_result)
from filodb_tpu.utils.observability import TRACER

TRACE_HEADER = "X-FiloDB-Trace-Id"
PARENT_SPAN_HEADER = "X-FiloDB-Parent-Span"


class HttpPlanDispatcher(PlanDispatcher):
    """Ships a leaf plan to ``endpoint`` and returns its result.

    Trace context crosses the process boundary twice over: the
    ``trace_id`` rides the execplan wire dict (QueryContext field) AND
    the HTTP headers; the data node returns its spans with the result
    so the coordinator's TraceStore holds ONE stitched tree."""

    def __init__(self, endpoint: str, timeout_s: float = 60.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def dispatch(self, plan, ctx: ExecContext) -> QueryResult:
        tid = plan.query_context.trace_id or ctx.query_context.trace_id \
            or TRACER.current_trace_id()
        if tid and not plan.query_context.trace_id:
            plan.query_context.trace_id = tid
        with TRACER.span("dispatch.http", endpoint=self.endpoint,
                         plan=type(plan).__name__,
                         shard=getattr(plan, "shard", "")) as sp:
            t0 = time.perf_counter()
            body = json.dumps(serialize_plan(plan)).encode()
            ser_s = time.perf_counter() - t0
            headers = {"Content-Type": "application/json"}
            if tid:
                headers[TRACE_HEADER] = tid
                headers[PARENT_SPAN_HEADER] = sp.span_id
            req = urllib.request.Request(
                f"{self.endpoint}/execplan", data=body, method="POST",
                headers=headers)
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as resp:
                    payload = json.loads(resp.read())
            except urllib.error.HTTPError as e:
                try:
                    err = json.loads(e.read()).get("error", "")
                except Exception:
                    err = f"HTTP {e.code}"
                raise QueryError(plan.query_context.query_id,
                                 f"remote dispatch to {self.endpoint} "
                                 f"failed: {err}") from e
            t1 = time.perf_counter()
            spans = payload.get("spans") if isinstance(payload, dict) else None
            if tid and spans:
                try:
                    from filodb_tpu.utils.forensics import TRACE_STORE
                    TRACE_STORE.ingest_remote(tid, spans)
                except Exception:  # noqa: BLE001 — stitching is best-effort
                    pass
            result = deserialize_result(payload)
            ctx.note_timing("serialize",
                            ser_s + (time.perf_counter() - t1))
            # remote stats fold into the coordinator's accounting exactly
            # like local leaves noting into the shared ctx
            ctx.absorb_stats(result.stats)
            return result

    def __repr__(self) -> str:
        return f"HttpPlanDispatcher({self.endpoint})"


def execplan_handler(memstore) -> Callable[..., dict]:
    """Server side: wire dict -> execute locally -> wire result.
    Transformers run here too (shard-local map/window work stays on the
    data node, as in the reference's remote QueryActor).  The originating
    query's trace context (wire field, or the HTTP headers passed as
    ``trace_parent``) is attached so this node's spans join the tree;
    they are shipped back under the ``spans`` key of the response."""

    def handle(payload: dict,
               trace_parent: Optional[tuple] = None) -> dict:
        plan = deserialize_plan(payload)
        tid = plan.query_context.trace_id or \
            (trace_parent[0] if trace_parent else None)
        # parent ONLY onto the caller's span id: any span still open on
        # this node (e.g. the leaf scheduler's run span enclosing this
        # handler) closes after the response's span list is built, so
        # parenting under it would orphan the whole remote subtree on
        # the coordinator in a real multi-process deployment
        parent_sid = trace_parent[1] if trace_parent else None
        ctx = ExecContext(memstore, plan.query_context)
        if not tid:
            return serialize_result(plan.execute(ctx))
        from filodb_tpu.utils.forensics import TRACE_STORE, span_to_dict
        with TRACER.attach((tid, parent_sid)):
            result = plan.execute(ctx)
        out = serialize_result(result)
        try:
            out["spans"] = [span_to_dict(r)
                            for r in TRACE_STORE.spans_for(tid)]
        except Exception:  # noqa: BLE001 — span return is best-effort
            pass
        return out

    return handle


def dispatcher_factory(mapper, endpoints: dict[str, str],
                       local_node: Optional[str] = None
                       ) -> Callable[[int], PlanDispatcher]:
    """shard -> dispatcher, from the ShardMapper's owner and a node ->
    endpoint map (the plug for SingleClusterPlanner.dispatcher_for_shard).
    Shards owned by ``local_node`` (or by unknown nodes) execute
    in-process."""
    from filodb_tpu.query.exec import IN_PROCESS

    cache: dict[str, HttpPlanDispatcher] = {}

    def for_shard(shard: int) -> PlanDispatcher:
        node = mapper.coord_for_shard(shard)
        if node is None or node == local_node:
            return IN_PROCESS
        endpoint = endpoints.get(node)
        if endpoint is None:
            # a remote-owned shard with no known endpoint must FAIL the
            # query, not silently scan an empty local store
            return _UnroutableDispatcher(shard, node)
        d = cache.get(node)
        if d is None:
            d = cache[node] = HttpPlanDispatcher(endpoint)
        return d

    return for_shard


class _UnroutableDispatcher(PlanDispatcher):
    def __init__(self, shard: int, node: str):
        self.shard = shard
        self.node = node

    def dispatch(self, plan, ctx) -> QueryResult:
        raise QueryError(
            plan.query_context.query_id,
            f"shard {self.shard} is owned by node {self.node!r} but no "
            f"endpoint is configured for it — refusing to return partial "
            f"results")
