"""Cross-node plan dispatch over HTTP.

Capability match for the reference's ActorPlanDispatcher (reference:
exec/PlanDispatcher.scala:29-46 — Akka ask of a Kryo-serialized ExecPlan
to the shard's owning node; remote QueryActor executes and replies with
a QueryResult; SURVEY.md §3.1 'PROCESS BOUNDARY').  Here the transport
is HTTP POST /execplan with the JSON wire format
(filodb_tpu/query/wire.py); the receiving node executes against its own
memstore and returns the serialized result.
"""

from __future__ import annotations

import collections
import concurrent.futures
import json
import threading
import time
import urllib.request
from typing import Callable, Optional

from filodb_tpu.query.exec import ExecContext, PlanDispatcher
from filodb_tpu.query.model import QueryError, QueryResult, ShardUnavailable
from filodb_tpu.query.wire import (deserialize_plan, deserialize_result,
                                   serialize_plan, serialize_result)
from filodb_tpu.utils.observability import TRACER
from filodb_tpu.workload import deadline as dl

TRACE_HEADER = "X-FiloDB-Trace-Id"
PARENT_SPAN_HEADER = "X-FiloDB-Parent-Span"

_WM = None


def _wm() -> dict:
    """The filodb_dispatch_* metric objects, resolved once per process
    (no registry-lock lookups on the dispatch hot path)."""
    global _WM
    if _WM is None:
        from filodb_tpu.utils.observability import workload_metrics
        _WM = workload_metrics()
    return _WM


_HEDGE_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None
_HEDGE_POOL_LOCK = threading.Lock()


def _hedge_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _HEDGE_POOL
    with _HEDGE_POOL_LOCK:
        if _HEDGE_POOL is None:
            _HEDGE_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="dispatch-hedge")
        return _HEDGE_POOL


class HttpPlanDispatcher(PlanDispatcher):
    """Ships a leaf plan to ``endpoint`` and returns its result.

    Trace context crosses the process boundary twice over: the
    ``trace_id`` rides the execplan wire dict (QueryContext field) AND
    the HTTP headers; the data node returns its spans with the result
    so the coordinator's TraceStore holds ONE stitched tree.

    Workload hardening (ISSUE 5):

    - every attempt's socket timeout is ``min(timeout_s cap, remaining
      deadline budget)`` — never a fixed constant (satellite #1 fix);
    - CONNECTION-level failures (refused/reset/DNS/socket timeout)
      retry up to ``max_retries`` times with exponential backoff, budget
      permitting; an HTTP response is never retried (the server spoke —
      re-asking multiplies load exactly when it must not);
    - with ``hedge=True`` a tail-slow first attempt triggers ONE hedged
      duplicate once it exceeds the dispatcher's observed p99 latency
      (read-only /execplan work is idempotent); first success wins;
    - a dispatch that exhausts retries raises :class:`ShardUnavailable`
      so scatter-gather can degrade to a warned partial result when the
      query allows it."""

    def __init__(self, endpoint: str, timeout_s: float = 60.0,
                 max_retries: int = 2, backoff_s: float = 0.05,
                 hedge: bool = False, hedge_min_s: float = 0.05,
                 hedge_warmup: int = 16, hedge_alternate=None):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s
        self.max_retries = max(int(max_retries), 0)
        self.backoff_s = float(backoff_s)
        self.hedge = bool(hedge)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_warmup = max(int(hedge_warmup), 1)
        # replica retarget hook (ISSUE 7): plan -> alternate ENDPOINT for
        # the hedged duplicate, chosen through ReplicaSet.pick (never an
        # ad-hoc list); None = hedge against the same endpoint (rf=1)
        self.hedge_alternate = hedge_alternate
        # recent successful-attempt latencies -> p99 hedge trigger
        self._lat: collections.deque = collections.deque(maxlen=128)
        self._lat_lock = threading.Lock()

    # -------------------------------------------------------------- transport

    def _note_latency(self, seconds: float) -> None:
        with self._lat_lock:
            self._lat.append(seconds)

    def hedge_delay_s(self) -> Optional[float]:
        """p99 of recent attempt latencies (floored at ``hedge_min_s``);
        None until ``hedge_warmup`` samples exist — hedging stays off
        until the trigger is data-driven."""
        with self._lat_lock:
            lat = sorted(self._lat)
        if len(lat) < self.hedge_warmup:
            return None
        return max(lat[min(int(0.99 * len(lat)), len(lat) - 1)],
                   self.hedge_min_s)

    def observed_p50_s(self) -> Optional[float]:
        """Median observed attempt latency — the calibrated-latency leg
        of ReplicaSet.pick's ordering (None until samples exist)."""
        with self._lat_lock:
            lat = sorted(self._lat)
        return lat[len(lat) // 2] if lat else None

    def _send_once(self, body: bytes, headers: dict,
                   deadline_timeout_s: float,
                   endpoint: Optional[str] = None) -> dict:
        req = urllib.request.Request(
            f"{endpoint or self.endpoint}/execplan", data=body,
            method="POST", headers=headers)
        t0 = time.perf_counter()
        with urllib.request.urlopen(req,
                                    timeout=deadline_timeout_s) as resp:
            payload = json.loads(resp.read())
        if endpoint is None:
            self._note_latency(time.perf_counter() - t0)
        return payload

    def _send_hedged(self, plan, make_body, headers: dict,
                     deadline_timeout_s: float) -> dict:
        """First attempt with a p99-armed hedge: when the primary is
        still in flight past the hedge delay, launch ONE duplicate and
        take whichever answers first.  With replicas, the duplicate
        retargets a DIFFERENT replica via the ``hedge_alternate`` hook
        (ReplicaSet.pick) — a wedged node cannot slow both requests.
        The WHOLE hedged attempt — hedge-delay wait included — stays
        inside ``deadline_timeout_s`` so a tail-latency storm cannot
        pin dispatch threads past the deadline they exist to enforce."""
        t_start = time.perf_counter()
        delay = self.hedge_delay_s()
        if delay is None or delay >= deadline_timeout_s:
            return self._send_once(make_body(), headers,
                                   deadline_timeout_s)
        pool = _hedge_pool()
        first = pool.submit(self._send_once, make_body(), headers,
                            deadline_timeout_s)
        try:
            return first.result(timeout=delay)
        except concurrent.futures.TimeoutError:
            pass  # tail-slow: hedge below
        m = _wm()
        m["dispatch_hedged"].inc(endpoint=self.endpoint)
        alt = self.hedge_alternate(plan) \
            if self.hedge_alternate is not None else None
        # retarget telemetry (counter + flight event) is emitted by the
        # hedge_alternate hook itself, where node NAMES are known — the
        # flight event's from/to domain must match _note_handoff's
        if alt is not None and alt.rstrip("/") == self.endpoint:
            alt = None
        # fresh body: the wire budget_ms re-encodes from what is left NOW
        second = pool.submit(self._send_once, make_body(), headers,
                             deadline_timeout_s, alt)
        pending = {first: "first", second: "second"}
        last_err: Optional[BaseException] = None
        while pending:
            budget_left = deadline_timeout_s \
                - (time.perf_counter() - t_start)
            if budget_left <= 0:
                break
            done, _ = concurrent.futures.wait(
                set(pending), timeout=budget_left,
                return_when=concurrent.futures.FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                tag = pending.pop(fut)
                err = fut.exception()
                if err is None:
                    if tag == "second":
                        m["dispatch_hedge_wins"].inc(
                            endpoint=self.endpoint)
                    return fut.result()
                last_err = err
        raise last_err if last_err is not None else TimeoutError(
            f"hedged dispatch to {self.endpoint} timed out")

    def _request(self, plan, make_body, headers: dict) -> dict:
        """Deadline-capped attempt loop: bounded retry-with-backoff on
        connection errors, optional p99 hedging on the first attempt.
        ``make_body`` re-serializes the plan PER ATTEMPT: the wire's
        relative ``budget_ms`` must reflect what is left NOW, not what
        was left before a failed attempt burned part of it — a stale
        body would let the data node re-anchor budget the coordinator
        already spent."""
        qctx = plan.query_context
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            rem = dl.remaining_ms(qctx)
            if rem is not None and rem <= 0:
                if last_err is None:
                    raise dl.DeadlineExceeded(
                        qctx.query_id,
                        f"deadline exhausted before dispatch to "
                        f"{self.endpoint}")
                break  # budget gone mid-retry: report the transport error
            deadline_timeout_s = dl.budget_timeout_s(qctx, self.timeout_s)
            try:
                if attempt == 0 and self.hedge:
                    return self._send_hedged(plan, make_body, headers,
                                             deadline_timeout_s)
                return self._send_once(make_body(), headers,
                                       deadline_timeout_s)
            except urllib.error.HTTPError:
                raise  # the server answered: never retry (load-safe)
            except (urllib.error.URLError, OSError) as e:
                last_err = e
                if attempt < self.max_retries:
                    _wm()["dispatch_retries"].inc(endpoint=self.endpoint)
                    pause = self.backoff_s * (2 ** attempt)
                    rem = dl.remaining_ms(qctx)
                    if rem is not None:
                        pause = min(pause, max(rem / 1000.0, 0.0))
                    if pause > 0:
                        time.sleep(pause)
        _wm()["dispatch_failures"].inc(endpoint=self.endpoint)
        raise ShardUnavailable(
            qctx.query_id,
            f"remote dispatch to {self.endpoint} failed after "
            f"{self.max_retries + 1} attempt(s): {last_err}") from last_err

    # --------------------------------------------------------------- dispatch

    def dispatch(self, plan, ctx: ExecContext) -> QueryResult:
        tid = plan.query_context.trace_id or ctx.query_context.trace_id \
            or TRACER.current_trace_id()
        if tid and not plan.query_context.trace_id:
            plan.query_context.trace_id = tid
        with TRACER.span("dispatch.http", endpoint=self.endpoint,
                         plan=type(plan).__name__,
                         shard=getattr(plan, "shard", "")) as sp:
            # serialized per attempt (see _request): the wire budget_ms
            # is encoded at build time; all builds land in the
            # serialize timing bucket
            ser_box = [0.0]

            def make_body():
                t0 = time.perf_counter()
                body = json.dumps(serialize_plan(plan)).encode()
                ser_box[0] += time.perf_counter() - t0
                return body

            headers = {"Content-Type": "application/json"}
            if tid:
                headers[TRACE_HEADER] = tid
                headers[PARENT_SPAN_HEADER] = sp.span_id
            try:
                payload = self._request(plan, make_body, headers)
            except urllib.error.HTTPError as e:
                try:
                    err = json.loads(e.read()).get("error", "")
                except Exception:
                    err = f"HTTP {e.code}"
                if e.code == 503:
                    # the data node REFUSED the work (overload / budget
                    # too small to finish): transport-class failure, so
                    # allow_partial_results can degrade it
                    su = ShardUnavailable(
                        plan.query_context.query_id,
                        f"remote dispatch to {self.endpoint} refused: "
                        f"{err}")
                    su.reason = "refused"
                    raise su from e
                raise QueryError(plan.query_context.query_id,
                                 f"remote dispatch to {self.endpoint} "
                                 f"failed: {err}") from e
            t1 = time.perf_counter()
            spans = payload.get("spans") if isinstance(payload, dict) else None
            if tid and spans:
                try:
                    from filodb_tpu.utils.forensics import TRACE_STORE
                    TRACE_STORE.ingest_remote(tid, spans)
                except Exception:  # noqa: BLE001 — stitching is best-effort
                    pass
            result = deserialize_result(payload)
            ctx.note_timing("serialize",
                            ser_box[0] + (time.perf_counter() - t1))
            # remote stats fold into the coordinator's accounting exactly
            # like local leaves noting into the shared ctx
            ctx.absorb_stats(result.stats)
            return result

    def __repr__(self) -> str:
        return f"HttpPlanDispatcher({self.endpoint})"


def execplan_handler(memstore) -> Callable[..., dict]:
    """Server side: wire dict -> execute locally -> wire result.
    Transformers run here too (shard-local map/window work stays on the
    data node, as in the reference's remote QueryActor).  The originating
    query's trace context (wire field, or the HTTP headers passed as
    ``trace_parent``) is attached so this node's spans join the tree;
    they are shipped back under the ``spans`` key of the response."""

    def handle(payload: dict,
               trace_parent: Optional[tuple] = None) -> dict:
        plan = deserialize_plan(payload)
        tid = plan.query_context.trace_id or \
            (trace_parent[0] if trace_parent else None)
        # parent ONLY onto the caller's span id: any span still open on
        # this node (e.g. the leaf scheduler's run span enclosing this
        # handler) closes after the response's span list is built, so
        # parenting under it would orphan the whole remote subtree on
        # the coordinator in a real multi-process deployment
        parent_sid = trace_parent[1] if trace_parent else None
        ctx = ExecContext(memstore, plan.query_context)
        if not tid:
            return serialize_result(plan.execute(ctx))
        from filodb_tpu.utils.forensics import TRACE_STORE, span_to_dict
        with TRACER.attach((tid, parent_sid)):
            result = plan.execute(ctx)
        out = serialize_result(result)
        try:
            out["spans"] = [span_to_dict(r)
                            for r in TRACE_STORE.spans_for(tid)]
        except Exception:  # noqa: BLE001 — span return is best-effort
            pass
        return out

    return handle


class ReplicaDispatcher(PlanDispatcher):
    """Failover router for one shard's replica group (ISSUE 7).

    Tries replicas in ReplicaSet.pick order; a TRANSPORT-level failure
    (``ShardUnavailable``: connect refused / retries exhausted / remote
    503 budget refusal) fails over to the next replica while deadline
    budget remains.  Only when the WHOLE group is exhausted does
    ``ShardUnavailable`` escape — the partial-results opt-in then
    degrades it exactly as before.  Every failover lands in the flight
    recorder (``dispatch.failover``) and
    ``filodb_dispatch_failover_total{reason=}``."""

    def __init__(self, dataset: str, shard: int, replica_set,
                 dispatcher_for_node: Callable[[int, str],
                                               Optional[PlanDispatcher]]):
        self.dataset = dataset
        self.shard = shard
        self.replica_set = replica_set
        self.dispatcher_for_node = dispatcher_for_node

    def dispatch(self, plan, ctx: ExecContext) -> QueryResult:
        order = self.replica_set.pick(self.shard)
        if not order:
            raise ShardUnavailable(
                plan.query_context.query_id,
                f"shard {self.shard} of {self.dataset} has no routable "
                f"replica (group down)")
        last_err: Optional[BaseException] = None
        for i, node in enumerate(order):
            if i > 0:
                rem = dl.remaining_ms(plan.query_context)
                if rem is not None and rem <= 0:
                    break  # budget gone: report the transport error
            # already-tried replicas are off limits for the hedge
            # retarget too (hedge_alternate_for reads this): a hedged
            # duplicate aimed at the replica that JUST failed would
            # nullify the hedge during the exact episode it exists for
            plan.replica_exclude = order[:i]
            d = self.dispatcher_for_node(self.shard, node)
            if d is None:
                last_err = ShardUnavailable(
                    plan.query_context.query_id,
                    f"shard {self.shard} replica on node {node!r} has no "
                    f"endpoint configured — refusing to serve it from "
                    f"the local store")
                last_err.reason = "no_endpoint"
                if i + 1 < len(order):
                    self._note_handoff(plan, node, order[i + 1],
                                       "no_endpoint", str(last_err))
                continue
            try:
                return d.dispatch(plan, ctx)
            except ShardUnavailable as e:
                last_err = e
                if i + 1 < len(order):
                    # the raise site tagged the failure class — never
                    # substring-match the message (urllib's "[Errno
                    # 111] Connection refused" reads as a work refusal)
                    self._note_handoff(plan, node, order[i + 1], e.reason,
                                       str(e))
        raise last_err if last_err is not None else ShardUnavailable(
            plan.query_context.query_id,
            f"shard {self.shard} of {self.dataset}: deadline exhausted "
            f"before any replica answered")

    def _note_handoff(self, plan, from_node: str, to_node: str,
                      reason: str, error: str) -> None:
        """Telemetry only — both nodes were already selected by pick();
        named to stay clear of the routing lint's site hints."""
        _wm()["dispatch_failover"].inc(reason=reason)
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("dispatch.failover", dataset=self.dataset,
                      shard=self.shard, from_node=from_node,
                      to_node=to_node, reason=reason,
                      trace_id=plan.query_context.trace_id or "",
                      error=error[:200])

    def __repr__(self) -> str:
        return f"ReplicaDispatcher({self.dataset}/{self.shard})"


def dispatcher_factory(mapper, endpoints: dict[str, str],
                       local_node: Optional[str] = None,
                       dispatch_config: Optional[dict] = None
                       ) -> Callable[[int], PlanDispatcher]:
    """shard -> dispatcher, from the ShardMapper's replica groups and a
    node -> endpoint map (the plug for
    SingleClusterPlanner.dispatcher_for_shard).  Single-copy shards keep
    the legacy shapes (IN_PROCESS / per-endpoint HttpPlanDispatcher);
    replicated shards route through a :class:`ReplicaDispatcher` whose
    candidate order — primary, failover, hedge retarget — always comes
    from ``ReplicaSet.pick``.  ``dispatch_config`` (the standalone
    ``workload.dispatch`` block) tunes the timeout cap / retries /
    hedging of the HTTP dispatchers it builds."""
    from filodb_tpu.coordinator.replicas import ReplicaSet
    from filodb_tpu.query.exec import IN_PROCESS

    cfg = dispatch_config or {}
    kwargs = dict(
        timeout_s=float(cfg.get("timeout-cap-s", 60.0)),
        max_retries=int(cfg.get("retries", 2)),
        backoff_s=float(cfg.get("backoff-s", 0.05)),
        hedge=bool(cfg.get("hedge", False)),
        hedge_min_s=float(cfg.get("hedge-min-s", 0.05)))
    cache: dict[str, HttpPlanDispatcher] = {}

    def latency_fn(node: str) -> Optional[float]:
        d = cache.get(node)
        return d.observed_p50_s() if d is not None else None

    replica_set = ReplicaSet(
        mapper, local_node=local_node, latency_fn=latency_fn,
        lag_tolerance_rows=int(cfg.get("lag-tolerance-rows", 256)))

    def hedge_alternate_for(plan, this_node: str) -> Optional[str]:
        """Endpoint for the hedged duplicate: the healthiest replica
        OTHER than the one already in flight AND the ones the failover
        loop already burned (plan.replica_exclude) — still via
        ReplicaSet.pick; None keeps same-endpoint hedging (rf=1)."""
        shard = getattr(plan, "shard", None)
        if shard is None:
            return None
        exclude = [this_node] + list(
            getattr(plan, "replica_exclude", ()))
        # walk down ReplicaSet.pick order past unusable candidates —
        # the local replica (serves in-process, not via a hedge POST)
        # and nodes with no configured endpoint — instead of degrading
        # to a same-endpoint hedge while a healthy remote peer idles
        # (mirrors the failover loop's no_endpoint continue)
        while True:
            node = replica_set.alternate(shard, exclude=exclude)
            if node is None or node == this_node:
                return None
            ep = endpoints.get(node)
            if node == local_node or ep is None:
                exclude = exclude + [node]
                continue
            this_ep = endpoints.get(this_node)
            if this_ep is not None \
                    and ep.rstrip("/") == this_ep.rstrip("/"):
                # two node names resolving to ONE endpoint
                # (misconfiguration): a "retarget" there is the same
                # wire target _send_hedged would discard — keep walking
                # for a genuinely different replica instead of emitting
                # ghost retarget telemetry for a hedge that never moves
                exclude = exclude + [node]
                continue
            # telemetry lives HERE, where node names are known: the
            # dispatch.failover event's from/to domain must match
            # ReplicaDispatcher._note_handoff (node names, not URLs)
            _wm()["dispatch_failover"].inc(reason="hedge_retarget")
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("dispatch.failover",
                          dataset=getattr(plan, "dataset", "") or "",
                          shard=shard, from_node=this_node, to_node=node,
                          reason="hedge_retarget",
                          trace_id=plan.query_context.trace_id or "")
            # normalized like HttpPlanDispatcher.__init__ — a trailing
            # slash would build "//execplan", missing the exact route
            return ep.rstrip("/")

    def http_for(node: str) -> Optional[HttpPlanDispatcher]:
        endpoint = endpoints.get(node)
        if endpoint is None:
            return None
        d = cache.get(node)
        if d is None:
            d = cache[node] = HttpPlanDispatcher(
                endpoint,
                hedge_alternate=lambda plan, _n=node:
                    hedge_alternate_for(plan, _n),
                **kwargs)
        return d

    def for_node(shard: int, node: str) -> Optional[PlanDispatcher]:
        if node == local_node:
            return IN_PROCESS
        return http_for(node)

    def for_shard(shard: int) -> PlanDispatcher:
        replicas = mapper.replicas(shard)
        if len(replicas) > 1:
            return ReplicaDispatcher(mapper.dataset, shard, replica_set,
                                     for_node)
        node = mapper.coord_for_shard(shard)
        if node is None or node == local_node:
            return IN_PROCESS
        d = http_for(node)
        if d is None:
            # a remote-owned shard with no known endpoint must FAIL the
            # query (or degrade to a warned partial result when the
            # query opts in), never silently scan an empty local store
            return _UnroutableDispatcher(shard, node)
        return d

    def mesh_feed(shard: int) -> bool:
        """True when THIS node's resident copy feeds the mesh fabric for
        ``shard`` (ISSUE 18): the replica choice routes through
        ``ReplicaSet.pick`` — the local copy serves the fused program
        iff it is the healthiest candidate, so a recovering or lagging
        local replica never silently feeds stale device grids."""
        order = replica_set.pick(shard)
        return bool(order) and order[0] == local_node

    for_shard.mesh_feed = mesh_feed
    return for_shard


class _UnroutableDispatcher(PlanDispatcher):
    def __init__(self, shard: int, node: str):
        self.shard = shard
        self.node = node

    def dispatch(self, plan, ctx) -> QueryResult:
        su = ShardUnavailable(
            plan.query_context.query_id,
            f"shard {self.shard} is owned by node {self.node!r} but no "
            f"endpoint is configured for it — refusing to serve it from "
            f"the local store")
        su.reason = "no_endpoint"
        raise su
