"""Elastic resharding: live power-of-two shard splits (ISSUE 13).

The reference (and this port, until now) fixes a dataset's shard count
at creation — shards bind 1:1 to source partitions at setup, and a hot
dataset can only grow by offline resharding.  This module doubles a
live dataset's shard count with zero serving downtime and zero lost or
double-counted rows, riding the PR 12 replica machinery end to end:

- Because shard assignment is a hash mask, parent shard ``s`` splits
  into children ``{s, s + N}`` (N = old count) for EVERY spread setting
  (``shardmap.shard_of_tags``; the generative sweep in
  tests/test_split.py proves it).  The lower half stays with the parent
  in place — only the upper half moves, and it moves as a REPLICA
  RECOVERY, not a data copy protocol of its own.

- Source partitions do not move: the child consumes the PARENT's
  partition (``shard % base`` at the stream factory), filtered to its
  half by ``TimeSeriesShard.split_ingest_filter``.  Parent and child
  offsets therefore live in one domain, so the child is literally a
  PR 12 recovering replica: it inherits the parent's persisted chunks
  + checkpoints (cloned under ``split_clone_lock`` so the pair is an
  at-rest snapshot), replays from the earliest checkpoint with the
  standard per-group watermark skipping, reports RecoveryInProgress,
  and is promoted at the replica group head through the existing
  watermark gate (``ShardMapper.group_head`` folds the parent's head
  for split children).  Live rows keep flowing to every copy through
  the unchanged publish paths — the broker partition log, or the
  ReplicaFanout dual-write lanes on queue transports.

Phase machine (persisted in the metastore KV, gossiped in ``/__health``
``topology`` payloads, adopted newest-generation-wins by every node):

    catchup   children registered as Recovery replicas on the parent's
              replica nodes; clones + replay run; queries still route
              the parent topology (children invisible to fan-out)
    serving   CUTOVER committed: one atomic Topology swap flips gateway
              sharding + query fan-out to 2N; parents exclude their
              migrated half at scan time (plan-time ``reshard_to``
              stamps — a query straddling the flip stays on the
              topology it planned against); parents still hold a full
              superset, so abort stays lossless
    retire    grace window elapsed: every node purges its parents'
              migrated partitions + persisted chunks and installs the
              retain-half ingest filter
    complete  split bookkeeping dropped (exclusions no longer needed)
    aborted   children discarded wholesale, topology reverted; the
              parent never stopped serving the full keyspace

Abort is first-class from any phase up to retire (the grace window IS
the abort horizon — once parents purge, the children are the only copy
of the migrated half).  Every phase + cursor persists, so a restarted
coordinator resumes (or an operator aborts) instead of wedging.

Rollup tier datasets (``<ds>_ds_<res>``) split in LOCKSTEP with their
source: same phases, children on the tier parents' replica nodes.  Tier
children REBUILD from their source children's rollup emissions (rolled
data is derived; the resolution router's conservative cluster boundary
routes raw until they catch up), so tier cutover needs no clone.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from typing import Callable, Optional

from filodb_tpu.core.record import parse_partkey
from filodb_tpu.parallel.shardmap import ShardStatus, shard_of_tags

_METRICS = None

PHASE_CODES = {"": 0, "none": 0, "prepare": 1, "catchup": 2, "serving": 3,
               "retire": 4, "complete": 5, "aborting": 6, "aborted": 6}

# phases an abort may interrupt: once RETIRE starts purging parents,
# the children are the only complete copy of the migrated half and a
# rollback would lose data — the grace window is the abort horizon
ABORTABLE_PHASES = ("prepare", "catchup", "serving")


def _m() -> dict:
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import split_metrics
        _METRICS = split_metrics()
    return _METRICS


def _record_key(dataset: str) -> str:
    return f"split::{dataset}"


def _clone_key(dataset: str, shard: int) -> str:
    return f"splitclone::{dataset}::{shard}"


def _retire_key(dataset: str) -> str:
    return f"splitretire::{dataset}"


class SplitController:
    """One per FiloServer.  Doubles as the split PARTICIPANT on every
    node (clone children, purge parents, clean up aborts — all driven
    by the gossiped topology) and the split COORDINATOR on the node
    that triggered it (phase machine + cutover/retire gates).  All
    mapper mutations go through the ShardManager lock; all phase state
    persists in the metastore KV before it takes effect, so a crash at
    any point resumes or aborts losslessly."""

    def __init__(self, node: str, manager, memstore, column_store,
                 meta_store,
                 peers: Optional[dict] = None,
                 resync: Optional[Callable[[], None]] = None,
                 transport_for: Optional[Callable[[str], str]] = None,
                 tiers_for: Optional[Callable[[str], list]] = None,
                 fresh_nodes: Optional[Callable[[], list]] = None,
                 spread_for: Optional[Callable[[str], int]] = None,
                 tick_interval_s: float = 0.25,
                 health_timeout_s: float = 1.5):
        self.node = node
        self.manager = manager
        self.memstore = memstore
        self.colstore = column_store
        self.metastore = meta_store
        self.peers = dict(peers or {})
        self._resync = resync or (lambda: None)
        # "broker" (shared partition log: children replay it directly)
        # or "queue" (ReplicaFanout dual-write; tier datasets)
        self.transport_for = transport_for or (lambda ds: "queue")
        self.tiers_for = tiers_for or (lambda ds: [])
        # liveness view for the quorum gate (standalone wires the
        # failure detector's fresh_nodes); None = no detector — fetch
        # every peer rather than treating them all as stale
        self.fresh_nodes = fresh_nodes
        self.spread_for = spread_for
        self.tick_interval_s = tick_interval_s
        self.health_timeout_s = health_timeout_s
        self._records: dict[str, dict] = {}   # guarded-by: _lock
        self._lock = threading.RLock()
        self._loop = None
        # chaos hooks (integrity/faultinject.py): a held transition
        # name stalls the phase machine right before that transition —
        # deterministic "kill mid-catch-up / partition mid-cutover"
        self._holds: set = set()              # guarded-by: _lock
        self._listeners: list = []
        self._clone_failed: dict = {}         # (ds, shard) -> error str

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        from filodb_tpu.utils.observability import PeriodicThread
        if self._loop is None:
            self._loop = PeriodicThread(self._tick, self.tick_interval_s,
                                        "split-controller")
            self._loop.start()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.stop()
            self._loop = None

    def load_persisted(self) -> None:
        """Read every persisted split record (before datasets set up)."""
        try:
            rows = self.metastore.list_kv("split::")
        except NotImplementedError:
            rows = {}
        with self._lock:
            for key, blob in rows.items():
                try:
                    rec = json.loads(blob)
                except ValueError:
                    continue
                self._records[rec["dataset"]] = rec

    def restore_dataset(self, dataset: str) -> None:
        """Re-apply a persisted split's topology to a freshly-built
        mapper (standalone start).  ``dataset`` may be the split root or
        one of its lockstep tiers; each mapper replays the transitions
        up to the recorded phase, so a coordinator restart resumes the
        split exactly where it persisted it."""
        with self._lock:
            for rec in self._records.values():
                if dataset != rec["dataset"] and \
                        dataset not in rec.get("tiers", ()):
                    continue
                phase = rec["phase"]
                if phase in ("aborted",):
                    return
                mapper = self.manager.mapper(dataset)
                with self.manager._lock:
                    if mapper.topology.split_phase is not None \
                            or mapper.total_shards >= rec["total"]:
                        return  # already applied / adopted
                    if phase == "aborting":
                        # abort persisted but not fully acked: the
                        # mapper simply stays on the parent topology
                        return
                    mapper.begin_split(spread=int(rec["spread"]))
                    for child, nodes in rec["children"].get(
                            dataset, {}).items():
                        mapper.register_split_child(int(child), nodes)
                    if phase in ("serving", "retire", "complete"):
                        mapper.commit_split()
                    if phase in ("retire", "complete"):
                        mapper.retire_split()
                    if phase == "complete":
                        mapper.finish_split()
                return

    # ---------------------------------------------------------- operations

    def trigger(self, dataset: str, grace_s: float = 30.0) -> dict:
        """Start a live N -> 2N split.  Children are placed on their
        parent's live replica nodes (the clone is a LOCAL read there;
        rebalancing is a separate, ordinary placement concern), in
        Recovery, invisible to query fan-out until cutover."""
        if dataset not in self.manager.datasets():
            raise KeyError(dataset)
        if self.transport_for(dataset) != "broker":
            raise ValueError(
                f"dataset {dataset!r} is not broker-sourced: live splits "
                f"replay the shared partition log for lossless catch-up "
                f"(queue-transport datasets would lose drained history)")
        with self._lock:
            rec = self._records.get(dataset)
            if rec is not None and rec["phase"] not in ("complete",
                                                        "aborted"):
                raise ValueError(
                    f"dataset {dataset!r} already has a split in flight "
                    f"(phase {rec['phase']})")
            for other in self._records.values():
                if dataset in other.get("tiers", ()) \
                        and other["phase"] not in ("complete", "aborted"):
                    raise ValueError(
                        f"{dataset!r} is a rollup tier of "
                        f"{other['dataset']!r}; split the source dataset")
            tiers = [t for t in self.tiers_for(dataset)
                     if t in self.manager.datasets()]
            spread = self._spread_of(dataset)
            children: dict[str, dict] = {}
            gens: dict[str, int] = {}
            with self.manager._lock:
                for ds in [dataset] + tiers:
                    mapper = self.manager.mapper(ds)
                    topo = mapper.begin_split(spread=spread)
                    base = topo.split_base
                    ch: dict[str, list] = {}
                    for parent in range(base):
                        nodes = [r.node for r in
                                 mapper.live_replicas(parent)] \
                            or [self.node]
                        child = parent + base
                        mapper.register_split_child(child, nodes)
                        ch[str(child)] = nodes
                    children[ds] = ch
                    gens[ds] = mapper.topology_generation
            rec = {"dataset": dataset, "base": len(children[dataset]),
                   "total": 2 * len(children[dataset]),
                   "spread": spread, "phase": "catchup",
                   "grace_s": float(grace_s), "tiers": tiers,
                   "children": children, "gens": gens,
                   "started_ms": int(time.time() * 1000),
                   "cutover_ms": None, "cutover_seconds": None,
                   "abort_reason": None, "owner": self.node}
            self._records[dataset] = rec  # filolint: disable=bounded-cache — keyed by operator-triggered dataset names, structurally bounded
            self._persist(rec)
        self._note_phase(dataset, "catchup")
        self.reconcile()
        self._resync()
        return self.status(dataset)

    def abort(self, dataset: str, reason: str = "operator abort") -> dict:
        """Lossless rollback from any phase before retire: children are
        discarded wholesale, the topology reverts in one generation
        bump, and the parents — which held a full superset throughout —
        just keep serving."""
        with self._lock:
            rec = self._records.get(dataset)
            mapper_split = self.manager.mapper(dataset).topology.split_phase
            if rec is None and mapper_split is None:
                raise ValueError(f"no split in flight for {dataset!r}")
            if rec is not None and rec["phase"] not in ABORTABLE_PHASES:
                raise ValueError(
                    f"split for {dataset!r} is in phase {rec['phase']} — "
                    f"abort is only lossless before retire purges the "
                    f"parents (tune grace-s for a longer abort horizon)")
            tiers = rec.get("tiers", []) if rec is not None \
                else [t for t in self.tiers_for(dataset)
                      if t in self.manager.datasets()]
            gens: dict[str, int] = {}
            with self.manager._lock:
                for ds in [dataset] + list(tiers):
                    mapper = self.manager.mapper(ds)
                    mapper.abort_split()
                    gens[ds] = mapper.topology_generation
            if rec is None:
                rec = {"dataset": dataset, "tiers": tiers, "children": {},
                       "grace_s": 0.0, "spread": self._spread_of(dataset),
                       "base": self.manager.mapper(dataset).num_shards,
                       "total": 0, "started_ms": int(time.time() * 1000),
                       "cutover_ms": None, "cutover_seconds": None,
                       "owner": self.node}
                self._records[dataset] = rec
            rec["phase"] = "aborting"
            rec["abort_reason"] = reason
            rec["gens"] = gens
            self._persist(rec)
        _m()["aborts"].inc(dataset=dataset)
        self._note_phase(dataset, "aborting")
        self.reconcile()
        self._resync()
        return self.status(dataset)

    # ---------------------------------------------------------- chaos hooks

    def hold(self, transition: str) -> None:
        """Stall the phase machine right before ``transition``
        ("cutover" | "retire" | "complete") — the deterministic latch
        the chaos harness uses to kill/partition nodes at an exact
        phase (integrity/faultinject.py)."""
        with self._lock:
            self._holds.add(transition)

    def release(self, transition: str) -> None:
        with self._lock:
            self._holds.discard(transition)

    def _held(self, transition: str) -> bool:
        with self._lock:
            return transition in self._holds

    def on_transition(self, fn: Callable[[str, str], None]) -> None:
        """Subscribe to (dataset, phase) transitions (chaos harness)."""
        self._listeners.append(fn)

    def _note_phase(self, dataset: str, phase: str) -> None:
        _m()["phase"].set(PHASE_CODES.get(phase, 0), dataset=dataset)
        try:
            _m()["generation"].set(
                self.manager.mapper(dataset).topology_generation,
                dataset=dataset)
        except KeyError:
            pass
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("split.phase", dataset=dataset, phase=phase,
                      node=self.node)
        for fn in list(self._listeners):
            try:
                fn(dataset, phase)
            except Exception:  # noqa: BLE001 — listeners never stall phases
                traceback.print_exc()

    # ------------------------------------------------------------- queries

    def status(self, dataset: str) -> Optional[dict]:
        with self._lock:
            rec = self._records.get(dataset)
            if rec is None:
                return None
            out = dict(rec)
        try:
            mapper = self.manager.mapper(dataset)
        except KeyError:
            return out
        topo = mapper.topology
        out["generation"] = topo.generation
        out["num_shards"] = topo.num_shards
        out["total_shards"] = mapper.total_shards
        children = []
        base = rec["base"]
        for child_s, nodes in sorted(rec["children"].get(dataset,
                                                         {}).items(),
                                     key=lambda kv: int(kv[0])):
            child = int(child_s)
            if child >= mapper.total_shards:
                continue
            st = mapper.state(child)
            serving = st.serving_replica()
            head = mapper.group_head(child)
            row = {"shard": child, "parent": child - base,
                   "nodes": nodes, "status": st.best_status.value,
                   "progress": serving.recovery_progress
                   if serving is not None else 0,
                   "watermark": serving.watermark
                   if serving is not None else -1,
                   "group_head": head}
            try:
                sh = self.memstore.get_shard(dataset, child)
                row["rows_replayed"] = sh.stats.rows_ingested
                row["rows_filtered"] = sh.stats.rows_split_filtered
            except Exception:  # noqa: BLE001 — not set up locally
                pass
            err = self._clone_failed.get((dataset, child))
            if err is not None:
                # a clone failing every tick stalls the split silently
                # otherwise — the operator sees the reason here
                row["clone_error"] = err
            children.append(row)
        out["children_status"] = children
        if rec.get("cutover_ms") and rec["phase"] == "serving":
            out["grace_remaining_s"] = max(
                0.0, rec["grace_s"]
                - (time.time() * 1000 - rec["cutover_ms"]) / 1000.0)
        return out

    def admin_state(self) -> dict:
        with self._lock:
            names = list(self._records)
        return {"node": self.node,
                "splits": [self.status(ds) for ds in names]}

    def split_progress(self) -> dict:
        """This node's participant progress, published in /__health so
        the coordinator can gate retire/complete on every node having
        actually purged (clone progress rides the ordinary replica
        status gossip)."""
        out: dict = {}
        for ds in self.manager.datasets():
            topo = self.manager.mapper(ds).topology
            if topo.split_phase is None:
                continue
            row = {"generation": topo.generation}
            if topo.split_phase == "retire":
                row["retired"] = self.metastore.read_kv(
                    _retire_key(ds)) is not None
            out[ds] = row
        return out

    def _marker_done(self, key: str, topo) -> bool:
        """A KV marker counts only when it was written under THIS split
        instance (the prepare-generation epoch) — a stale marker from a
        previous split of the same dataset must never satisfy a later
        one (it would skip the clone or, worse, the retire purge)."""
        return self.metastore.read_kv(key) == str(topo.split_epoch)

    def _mark_done(self, key: str, topo) -> None:
        self.metastore.write_kv(key, str(topo.split_epoch))

    def startable_shards(self, dataset: str, shards: list) -> list:
        """Gate for resync: a split child must not start consuming until
        its local clone (chunks + checkpoints) landed — starting earlier
        would replay from nothing and miss the pre-checkpoint history."""
        mapper = self.manager.mapper(dataset)
        topo = mapper.topology
        if topo.split_phase != "catchup":
            return list(shards)
        out = []
        for s in shards:
            if mapper.split_parent_of(s) is None:
                out.append(s)
            elif self.transport_for(dataset) != "broker" \
                    or self._marker_done(_clone_key(dataset, s), topo):
                out.append(s)
        return out

    # -------------------------------------------------------- shard hooks

    def on_shard_setup(self, dataset: str, shard) -> None:
        """memstore.shard_setup_hook: installs split filters on shards
        the moment they are created, BEFORE any ingest can reach them."""
        self._apply_shard_policy(dataset, shard)

    def _apply_shard_policy(self, dataset: str, shard) -> None:
        try:
            mapper = self.manager.mapper(dataset)
        except KeyError:
            return
        topo = mapper.topology
        if topo.split_phase is None:
            return
        total = topo.total_shards
        spread = topo.split_spread or 0
        num = shard.shard_num
        if mapper.split_parent_of(num) is not None:
            # split child: keep only its half of the replayed parent
            # partition, from the very first container
            shard.split_ingest_filter = (
                lambda tags, _t=total, _sp=spread, _s=num:
                shard_of_tags(tags, _t, _sp) == _s)
        elif topo.split_phase == "retire" and num < (topo.split_base or 0):
            # retired parent: refuse to re-materialize migrated series
            # (straggler publishers on the old generation)
            shard.split_ingest_filter = (
                lambda tags, _t=total, _sp=spread, _s=num:
                shard_of_tags(tags, _t, _sp) == _s)

    # ------------------------------------------------------------- driving

    def _tick(self) -> None:
        try:
            self.reconcile()
            with self._lock:
                records = [dict(r) for r in self._records.values()
                           if r.get("owner") == self.node]
            for rec in records:
                self._drive(rec)
            self._refresh_metrics()
        except Exception:  # noqa: BLE001 — keep ticking, loudly
            traceback.print_exc()

    def _refresh_metrics(self) -> None:
        with self._lock:
            recs = list(self._records.values())
        for rec in recs:
            ds = rec["dataset"]
            _m()["phase"].set(PHASE_CODES.get(rec["phase"], 0), dataset=ds)
            try:
                mapper = self.manager.mapper(ds)
            except KeyError:
                continue
            _m()["generation"].set(mapper.topology_generation, dataset=ds)
            if rec["phase"] in ("catchup", "serving"):
                rows = sum(sh.stats.rows_ingested
                           for sh in self.memstore.shards(ds)
                           if sh.shard_num >= rec["base"])
                _m()["replayed_rows"].set(rows, dataset=ds)
            if rec.get("cutover_seconds") is not None:
                _m()["cutover_seconds"].set(rec["cutover_seconds"],
                                            dataset=ds)

    def _drive(self, rec: dict) -> None:
        phase = rec["phase"]
        ds = rec["dataset"]
        if phase in ("catchup", "serving", "retire") \
                and self._reconcile_record_with_topology(rec):
            return
        if phase == "catchup":
            if self._held("cutover"):
                return
            if not self._children_caught_up(rec):
                return
            if not self._peers_ready(rec["gens"]):
                return
            self._do_cutover(rec)
        elif phase == "serving":
            if self._held("retire"):
                return
            cut = rec.get("cutover_ms") or 0
            if time.time() * 1000 - cut < rec["grace_s"] * 1000.0:
                return
            if not self._peers_ready(rec["gens"]):
                return
            self._do_retire(rec)
        elif phase == "retire":
            if self._held("complete"):
                return
            if self.metastore.read_kv(_retire_key(ds)) is None:
                return  # local purge not done yet (reconcile runs it)
            for t in rec.get("tiers", ()):
                if self.metastore.read_kv(_retire_key(t)) is None:
                    return
            if not self._peers_ready(rec["gens"], require_retired=rec):
                return
            self._do_complete(rec)
        elif phase == "aborting":
            if not self._peers_ready(rec["gens"]):
                return
            with self._lock:
                rec = self._records.get(ds) or rec
                if rec["phase"] != "aborting":
                    return
                rec["phase"] = "aborted"
                self._persist(rec)
            self._note_phase(ds, "aborted")

    def _reconcile_record_with_topology(self, rec: dict) -> bool:
        """An abort issued on ANOTHER node reaches this (owner) node as
        an adopted topology with the split gone — the owned record must
        follow, or it would march its phases against a reverted mapper
        (vacuously-true gates) and its restart would resurrect the
        aborted split at generations gossip can never override.
        Returns True when the record was retired from driving."""
        ds = rec["dataset"]
        try:
            mapper = self.manager.mapper(ds)
        except KeyError:
            return True
        if mapper.topology.split_phase is not None:
            return False
        final = "aborted" if mapper.total_shards <= rec["base"] \
            else "complete"
        with self._lock:
            live = self._records.get(ds)
            if live is None or live["phase"] != rec["phase"]:
                return True
            live["phase"] = final
            if final == "aborted" and not live.get("abort_reason"):
                live["abort_reason"] = "aborted elsewhere (adopted)"
            self._persist(live)
        self._note_phase(ds, final)
        return True

    def _do_cutover(self, rec: dict) -> None:
        ds = rec["dataset"]
        t0 = time.monotonic()
        gens: dict[str, int] = {}
        with self._lock:
            live = self._records.get(ds)
            if live is None or live["phase"] != "catchup":
                return
            with self.manager._lock:
                for name in [ds] + list(rec.get("tiers", ())):
                    mapper = self.manager.mapper(name)
                    if mapper.topology.split_phase == "catchup":
                        mapper.commit_split()
                    gens[name] = mapper.topology_generation
            live["phase"] = "serving"
            live["gens"] = gens
            live["cutover_ms"] = int(time.time() * 1000)
            live["cutover_seconds"] = round(time.monotonic() - t0, 6)
            self._persist(live)
        _m()["cutover_seconds"].set(rec["cutover_seconds"]
                                    if rec.get("cutover_seconds") else
                                    time.monotonic() - t0, dataset=ds)
        self._note_phase(ds, "serving")
        self._resync()

    def _do_retire(self, rec: dict) -> None:
        ds = rec["dataset"]
        gens: dict[str, int] = {}
        with self._lock:
            live = self._records.get(ds)
            if live is None or live["phase"] != "serving":
                return
            with self.manager._lock:
                for name in [ds] + list(rec.get("tiers", ())):
                    mapper = self.manager.mapper(name)
                    if mapper.topology.split_phase == "serving":
                        mapper.retire_split()
                    gens[name] = mapper.topology_generation
            live["phase"] = "retire"
            live["gens"] = gens
            self._persist(live)
        self._note_phase(ds, "retire")
        self.reconcile()   # purge locally right away

    def _do_complete(self, rec: dict) -> None:
        ds = rec["dataset"]
        with self._lock:
            live = self._records.get(ds)
            if live is None or live["phase"] != "retire":
                return
            with self.manager._lock:
                for name in [ds] + list(rec.get("tiers", ())):
                    mapper = self.manager.mapper(name)
                    if mapper.topology.split_phase == "retire":
                        mapper.finish_split()
            live["phase"] = "complete"
            self._persist(live)
        self._note_phase(ds, "complete")

    # --------------------------------------------------------------- gates

    def _children_caught_up(self, rec: dict) -> bool:
        """Cutover gate: every child group's serving replica passed the
        PR 12 promotion gate (ACTIVE at the group head) — or sits in
        RECOVERY with offset evidence it has nothing left to replay (a
        quiescent partition delivers no element to trip the in-stream
        promotion, but its offsets don't lie).  Additionally every
        LOCALLY-held child must have replayed past what its local
        parent had ingested when the check started (read parent first:
        monotone, so a pass can never go stale — post-cutover rows keep
        flowing to both halves of the parent partition)."""
        for ds in [rec["dataset"]] + list(rec.get("tiers", ())):
            mapper = self.manager.mapper(ds)
            topo = mapper.topology
            if topo.split_phase != "catchup":
                continue
            base = topo.split_base or 0
            # offsets are comparable only on the broker transport (one
            # shared partition log); tier/queue children number their
            # own streams and rebuild from rollup emissions — their
            # readiness is the consumer being up (ACTIVE), with the
            # resolution router's conservative boundary covering the
            # rebuild window
            comparable = self.transport_for(ds) == "broker"
            for child_s in rec["children"].get(ds, {}):
                child = int(child_s)
                if not self._child_ready(ds, mapper, child, child - base,
                                         comparable):
                    return False
        return True

    def _child_ready(self, ds: str, mapper, child: int, parent: int,
                     offsets_comparable: bool = True) -> bool:
        def effective_offset(sh) -> int:
            # a shard that replayed nothing yet still "holds" everything
            # its (cloned) checkpoints cover — the persisted chunks ARE
            # that data
            wms = [w for w in sh.group_watermarks]
            return max([sh.latest_offset] + wms)

        st = mapper.state(child)
        best = st.best_status
        if not offsets_comparable:
            return best is ShardStatus.ACTIVE
        local_off = None
        p_off = None
        try:
            p_off = effective_offset(self.memstore.get_shard(ds, parent))
            local_off = effective_offset(self.memstore.get_shard(ds, child))
        except Exception:  # noqa: BLE001 — copies not held locally
            pass
        if best is ShardStatus.ACTIVE:
            # promotion gate passed; still require the monotone local
            # offset check when we can read both shards directly
            return local_off is None or local_off >= p_off
        if best is not ShardStatus.RECOVERY:
            return False
        if local_off is not None:
            return local_off >= p_off
        serving = st.serving_replica()
        wm = serving.watermark if serving is not None else -1
        head = mapper.group_head(child)
        return wm >= 0 and head >= 0 and wm >= head

    def _peers_ready(self, gens: dict, require_retired: Optional[dict]
                     = None) -> bool:
        """Phase-advance gate: a MAJORITY of the configured cluster
        (self included) must be reachable and have adopted at least the
        given generations (and, for the complete gate, report their
        parents purged).  A reachable-but-lagging peer stalls outright
        (it adopts within a gossip sweep); an unreachable peer simply
        doesn't count toward the quorum — so a killed minority cannot
        block the split, while a coordinator PARTITIONED from its peers
        can never advance phases alone (the mid-cutover chaos
        scenario): serving continues either way, and progress resumes
        on heal."""
        nodes = set(self.peers) | {self.node}
        if len(nodes) <= 1:
            return True
        # peers the failure detector already declared stale are not
        # fetched at all (no ack, no veto): a dead peer must not cost
        # this gate a connect timeout on every 250ms tick
        fresh = set(self.fresh_nodes()) if self.fresh_nodes is not None \
            else None
        acked = 1   # self, trivially at its own generations
        for peer, endpoint in self.peers.items():
            if peer == self.node \
                    or (fresh is not None and peer not in fresh):
                continue
            body = self._fetch_health(endpoint)
            if body is None:
                continue   # unreachable: no ack, no veto
            topo = body.get("topology") or {}
            for ds, gen in gens.items():
                peer_gen = int((topo.get(ds) or {}).get("generation", -1))
                if peer_gen < gen:
                    return False
            if require_retired is not None:
                prog = body.get("split_progress") or {}
                for ds in [require_retired["dataset"]] \
                        + list(require_retired.get("tiers", ())):
                    if not (prog.get(ds) or {}).get("retired"):
                        return False
            acked += 1
        return acked * 2 > len(nodes)

    def _fetch_health(self, endpoint: str) -> Optional[dict]:
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(f"{endpoint}/__health",
                                        timeout=self.health_timeout_s) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read())
            except Exception:  # noqa: BLE001
                return None
        except Exception:  # noqa: BLE001 — unreachable
            return None

    # --------------------------------------------------------- participant

    def reconcile(self) -> None:
        """Per-node participant duties, driven purely by the (gossiped)
        mapper topology — idempotent, crash-safe via KV markers."""
        resync_needed = False
        for ds in list(self.manager.datasets()):
            try:
                mapper = self.manager.mapper(ds)
            except KeyError:
                continue
            topo = mapper.topology
            if topo.split_phase == "catchup":
                resync_needed |= self._reconcile_catchup(ds, mapper)
            elif topo.split_phase in ("serving", "retire"):
                self._reconcile_parent_filters(ds, mapper)
                if topo.split_phase == "retire":
                    self._reconcile_retire(ds, mapper)
            elif topo.split_phase is None:
                resync_needed |= self._reconcile_orphans(ds, mapper)
        if resync_needed:
            self._resync()

    def _reconcile_catchup(self, ds: str, mapper) -> bool:
        """Clone parents' persisted state into locally-held children
        that lack their marker; returns True when a new clone completed
        (the child consumer can start now)."""
        started = False
        topo = mapper.topology
        base = topo.split_base or 0
        for child in range(base, mapper.total_shards):
            if mapper.state(child).replica(self.node) is None:
                continue
            # child filter may need retro-install (shard set up before
            # the topology was adopted on this node)
            try:
                sh = self.memstore.get_shard(ds, child)
                if sh.split_ingest_filter is None:
                    self._apply_shard_policy(ds, sh)
            except Exception:  # noqa: BLE001 — not set up yet (hook covers)
                pass
            if self.transport_for(ds) != "broker":
                continue   # tier children rebuild from rollup emissions
            if self._marker_done(_clone_key(ds, child), topo):
                continue
            if self._clone_child(ds, child, base, topo):
                started = True
        return started

    def _clone_child(self, ds: str, child: int, base: int, topo) -> bool:
        """Clone the parent's (persisted chunks, partkeys, checkpoints)
        into the child, filtered to the child's half, as one at-rest
        snapshot: ``split_clone_lock`` excludes the flush executor's
        persist->checkpoint pair, preserving the recovery invariant
        (checkpoints only cover persisted rows) on the child.  The
        child then replays the parent's partition from its earliest
        cloned checkpoint — the standard PR 12 recovery path."""
        parent = child - base
        try:
            parent_sh = self.memstore.get_shard(ds, parent)
        except Exception:  # noqa: BLE001 — parent not local: cannot clone
            return False
        total, spread = topo.total_shards, topo.split_spread or 0
        keep = (lambda pk, _t=total, _sp=spread, _c=child:
                shard_of_tags(parse_partkey(pk), _t, _sp) == _c)
        t0 = time.monotonic()
        try:
            with parent_sh.split_clone_lock:
                n = self.colstore.clone_shard(ds, parent, child, keep)
                for grp, off in self.metastore.read_checkpoints(
                        ds, parent).items():
                    self.metastore.write_checkpoint(ds, child, grp, off)
        except Exception as e:  # noqa: BLE001 — surface, retry next tick
            self._clone_failed[(ds, child)] = str(e)
            traceback.print_exc()
            return False
        self._clone_failed.pop((ds, child), None)
        self._mark_done(_clone_key(ds, child), topo)
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("split.clone", dataset=ds, shard=child,
                      parent=parent, chunks=n, node=self.node,
                      seconds=round(time.monotonic() - t0, 6))
        return True

    def _reconcile_parent_filters(self, ds: str, mapper) -> None:
        """Post-cutover: nothing to install on parents besides what the
        planner stamps per query; retired parents additionally get the
        ingest filter in _reconcile_retire.  Kept as a hook point so a
        late-setup parent shard re-applies policy."""
        topo = mapper.topology
        if topo.split_phase != "retire":
            return
        for parent in range(topo.split_base or 0):
            try:
                sh = self.memstore.get_shard(ds, parent)
            except Exception:  # noqa: BLE001 — not local
                continue
            if sh.split_ingest_filter is None:
                self._apply_shard_policy(ds, sh)

    def _reconcile_retire(self, ds: str, mapper) -> None:
        """Purge local parents' migrated halves once, marker-guarded.
        The PERSISTED side is swept independently of the in-memory
        purge result (store partkeys rehashed directly): a retry after
        a transient store failure must still delete the migrated
        chunks, or a restart would re-materialize series the child now
        owns."""
        topo = mapper.topology
        if self._marker_done(_retire_key(ds), topo):
            return
        total, spread = topo.total_shards, topo.split_spread or 0
        purged_total = 0
        for parent in range(topo.split_base or 0):
            try:
                sh = self.memstore.get_shard(ds, parent)
            except Exception:  # noqa: BLE001 — not held locally
                continue
            if sh.split_ingest_filter is None:
                self._apply_shard_policy(ds, sh)
            purged = sh.purge_resharded(total, spread)
            purged_total += len(purged)
            try:
                migrated = set(purged)
                migrated.update(
                    r.partkey
                    for r in self.colstore.scan_part_keys(ds, parent)
                    if shard_of_tags(parse_partkey(r.partkey), total,
                                     spread) != parent)
                if migrated:
                    self.colstore.delete_part_keys(ds, parent,
                                                   list(migrated))
            except Exception:  # noqa: BLE001 — store failure: NO marker,
                # retry next tick with the full store sweep intact
                traceback.print_exc()
                return
        self._mark_done(_retire_key(ds), topo)
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("split.retire", dataset=ds, node=self.node,
                      partitions_purged=purged_total)

    def _reconcile_orphans(self, ds: str, mapper) -> bool:
        """After an abort (topology shrank), discard local child shards
        beyond the shard space: stop/drop in-memory state, delete their
        cloned persisted rows + checkpoints + markers.  The parents were
        never touched, so this is the whole cleanup."""
        total = mapper.total_shards
        orphans = [sh.shard_num for sh in self.memstore.shards(ds)
                   if sh.shard_num >= total]
        if not orphans:
            return False
        for s in orphans:
            self.memstore.drop_shard(ds, s)
            try:
                self.colstore.delete_shard(ds, s)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            try:
                self.metastore.delete_checkpoints(ds, s)
            except NotImplementedError:
                pass
            self.metastore.delete_kv(_clone_key(ds, s))
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("split.discard_child", dataset=ds, shard=s,
                          node=self.node)
        self.metastore.delete_kv(_retire_key(ds))
        return True

    # ------------------------------------------------------------ plumbing

    def _spread_of(self, dataset: str) -> int:
        """The dataset's INGEST spread — membership in a half is decided
        with the same bit-splice the gateway routes with."""
        fn = getattr(self, "spread_for", None)
        if fn is not None:
            try:
                return int(fn(dataset))
            except Exception:  # noqa: BLE001
                pass
        return 1

    def _persist(self, rec: dict) -> None:
        try:
            self.metastore.write_kv(_record_key(rec["dataset"]),
                                    json.dumps(rec))
        except NotImplementedError:
            pass
