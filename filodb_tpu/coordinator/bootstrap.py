"""Cluster bootstrap: seed discovery + join.

Capability match for the reference's akka-bootstrapper (reference:
akka-bootstrapper/src/main/scala/.../AkkaBootstrapper.scala:31 —
bootstrap() discovers seeds then joins the cluster;
ExplicitListClusterSeedDiscovery.scala:18 and
DnsSrvClusterSeedDiscovery.scala:12 strategies).  Discovery yields peer
HTTP endpoints; joining = heartbeating the local node into the
FailureDetector and probing peers' /__health so live peers register too.
"""

from __future__ import annotations

import json
import socket
import threading
import urllib.request
from typing import Optional, Sequence

from filodb_tpu.coordinator.cluster import FailureDetector


class SeedDiscovery:
    def discover(self) -> list[str]:
        """Returns peer endpoints, e.g. ['http://host:8080', ...]."""
        raise NotImplementedError


class ExplicitListSeedDiscovery(SeedDiscovery):
    """Static seed list (reference: ExplicitListClusterSeedDiscovery)."""

    def __init__(self, seeds: Sequence[str]):
        self.seeds = list(seeds)

    def discover(self) -> list[str]:
        return list(self.seeds)


class DnsSeedDiscovery(SeedDiscovery):
    """Resolve one DNS name to its A records (headless-service style;
    reference: DnsSrvClusterSeedDiscovery — SRV lookups need a resolver
    lib, A-record fan-out covers the k8s headless-service case)."""

    def __init__(self, hostname: str, port: int, scheme: str = "http"):
        self.hostname = hostname
        self.port = port
        self.scheme = scheme

    def discover(self) -> list[str]:
        try:
            infos = socket.getaddrinfo(self.hostname, self.port,
                                       type=socket.SOCK_STREAM)
        except socket.gaierror:
            return []
        addrs = sorted({i[4][0] for i in infos})
        return [f"{self.scheme}://{a}:{self.port}" for a in addrs]


class ClusterBootstrap:
    """Join protocol: register self, probe discovered peers, keep
    heartbeating them while they answer /__health (reference:
    AkkaBootstrapper.bootstrap + Akka gossip keeping membership fresh)."""

    def __init__(self, node: str, detector: FailureDetector,
                 discovery: SeedDiscovery, probe_timeout_s: float = 5.0):
        self.node = node
        self.detector = detector
        self.discovery = discovery
        self.probe_timeout_s = probe_timeout_s
        self.peers: dict[str, str] = {}  # node name -> endpoint
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe(self, endpoint: str) -> Optional[str]:
        """Health-check a peer; returns its node name if alive."""
        try:
            with urllib.request.urlopen(f"{endpoint}/__health",
                                        timeout=self.probe_timeout_s) as r:
                body = json.loads(r.read())
        except Exception:  # noqa: BLE001 — dead peer is a normal outcome
            return None
        # prefer the explicit node name; fall back to shard-status owners.
        # NEVER invent a name (an endpoint-as-name would register a phantom
        # node the shard manager could assign work to)
        if body.get("node"):
            return body["node"]
        for statuses in body.get("shards", {}).values():
            for st in statuses:
                if st.get("node"):
                    return st["node"]
        return None

    def bootstrap(self) -> list[str]:
        """One discovery+join round; returns peers found alive."""
        self.detector.heartbeat(self.node)
        alive = []
        for endpoint in self.discovery.discover():
            name = self.probe(endpoint)
            if name is not None and name != self.node:
                self.peers[name] = endpoint
                self.detector.heartbeat(name)
                alive.append(name)
        return alive

    def start_background(self, interval_s: float = 5.0) -> None:
        """Keep membership fresh: re-probe peers and sweep the failure
        detector on an interval."""
        def loop():
            while not self._stop.wait(interval_s):
                self.bootstrap()
                self.detector.check()
        self._thread = threading.Thread(target=loop, name="bootstrap",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
