"""Cluster bootstrap: seed discovery + join.

Capability match for the reference's akka-bootstrapper (reference:
akka-bootstrapper/src/main/scala/.../AkkaBootstrapper.scala:31 —
bootstrap() discovers seeds then joins the cluster;
ExplicitListClusterSeedDiscovery.scala:18 and
DnsSrvClusterSeedDiscovery.scala:12 strategies).  Discovery yields peer
HTTP endpoints; joining = heartbeating the local node into the
FailureDetector and probing peers' /__health so live peers register too.
"""

from __future__ import annotations

import json
import socket
import threading
import urllib.parse
import urllib.request
from typing import Optional, Sequence

from filodb_tpu.coordinator.cluster import FailureDetector


class SeedDiscovery:
    def discover(self) -> list[str]:
        """Returns peer endpoints, e.g. ['http://host:8080', ...]."""
        raise NotImplementedError


class ExplicitListSeedDiscovery(SeedDiscovery):
    """Static seed list (reference: ExplicitListClusterSeedDiscovery)."""

    def __init__(self, seeds: Sequence[str]):
        self.seeds = list(seeds)

    def discover(self) -> list[str]:
        return list(self.seeds)


class DnsSeedDiscovery(SeedDiscovery):
    """Resolve one DNS name to its A records (headless-service style;
    reference: DnsSrvClusterSeedDiscovery — SRV lookups need a resolver
    lib, A-record fan-out covers the k8s headless-service case)."""

    def __init__(self, hostname: str, port: int, scheme: str = "http"):
        self.hostname = hostname
        self.port = port
        self.scheme = scheme

    def discover(self) -> list[str]:
        try:
            infos = socket.getaddrinfo(self.hostname, self.port,
                                       type=socket.SOCK_STREAM)
        except socket.gaierror:
            return []
        addrs = sorted({i[4][0] for i in infos})
        return [f"{self.scheme}://{a}:{self.port}" for a in addrs]


class DnsSrvSeedDiscovery(SeedDiscovery):
    """True DNS SRV discovery (reference:
    DnsSrvClusterSeedDiscovery.scala:12 — resolves
    ``_service._proto.domain`` SRV records to host:port seeds).

    No resolver library may be installed here, so this speaks the DNS
    wire format directly over UDP (RFC 1035/2782): one SRV query to the
    configured resolver, answers sorted by (priority, -weight), targets
    resolved to addresses via getaddrinfo."""

    def __init__(self, srv_name: str, scheme: str = "http",
                 resolver: Optional[tuple[str, int]] = None,
                 timeout_s: float = 3.0):
        self.srv_name = srv_name.rstrip(".")
        self.scheme = scheme
        self.resolver = resolver or self._system_resolver()
        self.timeout_s = timeout_s

    @staticmethod
    def _system_resolver() -> tuple[str, int]:
        try:
            with open("/etc/resolv.conf") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0] == "nameserver":
                        return parts[1], 53
        except OSError:
            pass
        return "127.0.0.1", 53

    def _build_query(self, qid: int) -> bytes:
        out = bytearray()
        out += qid.to_bytes(2, "big")
        out += (0x0100).to_bytes(2, "big")      # RD=1
        out += (1).to_bytes(2, "big")           # QDCOUNT
        out += (0).to_bytes(6, "big")           # AN/NS/AR
        for label in self.srv_name.split("."):
            lb = label.encode()
            out += bytes([len(lb)]) + lb
        out += b"\x00"
        out += (33).to_bytes(2, "big")          # QTYPE=SRV
        out += (1).to_bytes(2, "big")           # QCLASS=IN
        return bytes(out)

    @staticmethod
    def _read_name(buf: bytes, pos: int) -> tuple[str, int]:
        """Parse a (possibly compressed) DNS name; returns (name, next)."""
        labels = []
        jumped = False
        nxt = pos
        hops = 0
        while True:
            if pos >= len(buf):
                raise ValueError("truncated name")
            ln = buf[pos]
            if ln & 0xC0 == 0xC0:               # compression pointer
                if pos + 2 > len(buf):
                    raise ValueError("truncated pointer")
                if not jumped:
                    nxt = pos + 2
                pos = ((ln & 0x3F) << 8) | buf[pos + 1]
                jumped = True
                hops += 1
                if hops > 32:
                    raise ValueError("compression loop")
                continue
            pos += 1
            if ln == 0:
                break
            labels.append(buf[pos:pos + ln].decode("ascii",
                                                   errors="replace"))
            pos += ln
        return ".".join(labels), (nxt if jumped else pos)

    def _parse_srv_answers(self, buf: bytes) -> list[tuple[int, int, int, str]]:
        if len(buf) < 12:
            raise ValueError("short DNS response")
        qd = int.from_bytes(buf[4:6], "big")
        an = int.from_bytes(buf[6:8], "big")
        pos = 12
        for _ in range(qd):                     # skip question section
            _, pos = self._read_name(buf, pos)
            pos += 4
        out = []
        for _ in range(an):
            _, pos = self._read_name(buf, pos)
            rtype = int.from_bytes(buf[pos:pos + 2], "big")
            rdlen = int.from_bytes(buf[pos + 8:pos + 10], "big")
            rdata = buf[pos + 10:pos + 10 + rdlen]
            pos += 10 + rdlen
            if rtype != 33 or len(rdata) < 7:
                continue
            prio = int.from_bytes(rdata[0:2], "big")
            weight = int.from_bytes(rdata[2:4], "big")
            port = int.from_bytes(rdata[4:6], "big")
            # target name may use compression into the full message
            target, _ = self._read_name(buf, pos - rdlen + 6)
            out.append((prio, weight, port, target))
        return out

    def _query_tcp(self, query: bytes) -> Optional[bytes]:
        """RFC 1035 TCP fallback: 2-byte length-prefixed framing."""
        try:
            with socket.create_connection(self.resolver,
                                          timeout=self.timeout_s) as sk:
                sk.sendall(len(query).to_bytes(2, "big") + query)
                hdr = sk.recv(2)
                if len(hdr) < 2:
                    return None
                want = int.from_bytes(hdr, "big")
                buf = b""
                while len(buf) < want:
                    chunk = sk.recv(want - len(buf))
                    if not chunk:
                        return None
                    buf += chunk
                return buf
        except OSError:
            return None

    def discover(self) -> list[str]:
        import os
        qid = int.from_bytes(os.urandom(2), "big")
        query = self._build_query(qid)
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sk:
                sk.settimeout(self.timeout_s)
                sk.sendto(query, self.resolver)
                resp, _ = sk.recvfrom(4096)
        except OSError:
            return []
        if len(resp) >= 3 and resp[2] & 0x02:
            # TC bit: the resolver truncated a large SRV answer at the
            # classic UDP limit — retry over TCP for the full response
            resp = self._query_tcp(query) or b""
        if len(resp) < 2 or resp[:2] != query[:2]:
            return []
        try:
            answers = self._parse_srv_answers(resp)
        except ValueError:
            return []
        answers.sort(key=lambda a: (a[0], -a[1]))
        seeds = []
        for _, _, port, target in answers:
            try:
                infos = socket.getaddrinfo(target, port,
                                           type=socket.SOCK_STREAM)
                addrs = sorted({i[4][0] for i in infos})
            except socket.gaierror:
                addrs = [target]
            seeds.extend(f"{self.scheme}://{a}:{port}" for a in addrs)
        return seeds


class ConsulSeedDiscovery(SeedDiscovery):
    """Consul health-API discovery (reference: ConsulClusterSeedDiscovery
    + ConsulClient.scala): GET
    ``/v1/health/service/<name>?passing=1`` and turn each passing
    instance's (Service.Address|Node.Address, Service.Port) into a seed
    endpoint."""

    def __init__(self, service: str, consul_url: str = "http://127.0.0.1:8500",
                 scheme: str = "http", timeout_s: float = 3.0):
        self.service = service
        self.consul_url = consul_url.rstrip("/")
        self.scheme = scheme
        self.timeout_s = timeout_s

    def discover(self) -> list[str]:
        url = (f"{self.consul_url}/v1/health/service/"
               f"{urllib.parse.quote(self.service)}?passing=1")
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                entries = json.loads(r.read())
        except Exception:  # noqa: BLE001 — consul down: no seeds
            return []
        seeds = []
        for e in entries if isinstance(entries, list) else []:
            svc = e.get("Service") or {}
            node = e.get("Node") or {}
            addr = svc.get("Address") or node.get("Address")
            port = svc.get("Port")
            if addr and port:
                seeds.append(f"{self.scheme}://{addr}:{port}")
        return seeds


def seed_discovery_from_config(conf: dict) -> SeedDiscovery:
    """Config-driven strategy pick (reference: the bootstrapper's
    ``discovery-mechanism`` setting)."""
    kind = conf.get("mechanism", "explicit")
    if kind == "explicit":
        return ExplicitListSeedDiscovery(conf.get("seeds", []))
    if kind == "dns-a":
        return DnsSeedDiscovery(conf["hostname"], int(conf["port"]),
                                conf.get("scheme", "http"))
    if kind == "dns-srv":
        resolver = None
        if conf.get("resolver"):
            host, _, port = conf["resolver"].partition(":")
            resolver = (host, int(port or 53))
        return DnsSrvSeedDiscovery(conf["srv-name"],
                                   conf.get("scheme", "http"),
                                   resolver=resolver)
    if kind == "consul":
        return ConsulSeedDiscovery(conf["service"],
                                   conf.get("consul-url",
                                            "http://127.0.0.1:8500"),
                                   conf.get("scheme", "http"))
    raise ValueError(f"unknown discovery mechanism {kind!r}")


class ClusterBootstrap:
    """Join protocol: register self, probe discovered peers, keep
    heartbeating them while they answer /__health (reference:
    AkkaBootstrapper.bootstrap + Akka gossip keeping membership fresh)."""

    def __init__(self, node: str, detector: FailureDetector,
                 discovery: SeedDiscovery, probe_timeout_s: float = 5.0):
        self.node = node
        self.detector = detector
        self.discovery = discovery
        self.probe_timeout_s = probe_timeout_s
        self.peers: dict[str, str] = {}  # node name -> endpoint
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe(self, endpoint: str) -> Optional[str]:
        """Health-check a peer; returns its node name if alive."""
        try:
            with urllib.request.urlopen(f"{endpoint}/__health",
                                        timeout=self.probe_timeout_s) as r:
                body = json.loads(r.read())
        except Exception:  # noqa: BLE001 — dead peer is a normal outcome
            return None
        # prefer the explicit node name; fall back to shard-status owners.
        # NEVER invent a name (an endpoint-as-name would register a phantom
        # node the shard manager could assign work to)
        if body.get("node"):
            return body["node"]
        for statuses in body.get("shards", {}).values():
            for st in statuses:
                if st.get("node"):
                    return st["node"]
        return None

    def bootstrap(self) -> list[str]:
        """One discovery+join round; returns peers found alive."""
        self.detector.heartbeat(self.node)
        alive = []
        for endpoint in self.discovery.discover():
            name = self.probe(endpoint)
            if name is not None and name != self.node:
                self.peers[name] = endpoint
                self.detector.heartbeat(name)
                alive.append(name)
        return alive

    def start_background(self, interval_s: float = 5.0) -> None:
        """Keep membership fresh: re-probe peers and sweep the failure
        detector on an interval."""
        def loop():
            while not self._stop.wait(interval_s):
                self.bootstrap()
                self.detector.check()
        self._thread = threading.Thread(target=loop, name="bootstrap",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
