"""Advanced query planners: time-range routing, HA, federation, regex keys.

Capability match for the reference's planner suite (reference:
coordinator/src/main/scala/filodb.coordinator/queryplanner/):
- LongTimeRangePlanner.scala — route raw vs downsample clusters by the
  query's time range, stitching when it spans both;
- HighAvailabilityPlanner.scala + FailureProvider — route around failure
  time-ranges to a remote replica via PromQL-over-HTTP;
- MultiPartitionPlanner.scala + PartitionLocationProvider — federate a
  query across FiloDB installations;
- SinglePartitionPlanner.scala — pick a planner per query by its metric;
- ShardKeyRegexPlanner.scala — expand regex shard-key filters into
  concrete shard keys and concatenate/aggregate the results;
- LogicalPlanUtils.scala — copyWithUpdatedTimeRange.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex
from filodb_tpu.coordinator.planner import QueryPlanner
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import (DistConcatExec, EmptyResultExec, ExecPlan,
                                   ReduceAggregateExec, StitchRvsExec)
from filodb_tpu.query.model import QueryContext
from filodb_tpu.query.transformers import (AggregatePresenter,
                                           StitchRvsMapper)


# ---------------------------------------------------------------------------
# LogicalPlanUtils: time-range rewrite (reference: LogicalPlanUtils.scala:238
# copyWithUpdatedTimeRange)
# ---------------------------------------------------------------------------


def copy_with_time_range(plan: lp.LogicalPlan, start_ms: int,
                         end_ms: int) -> lp.LogicalPlan:
    """Recursively rebuild a periodic plan for a new [start, end]; the raw
    interval selectors are re-derived from lookback/window + offset."""
    if isinstance(plan, lp.RawSeries):
        look = plan.lookback_ms or 0
        off = plan.offset_ms or 0
        return dataclasses.replace(
            plan, range_selector=lp.IntervalSelector(start_ms - look - off,
                                                     end_ms - off))
    if not dataclasses.is_dataclass(plan):
        return plan
    updates = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, lp.RawSeries):
            look = (v.lookback_ms or 0) + getattr(plan, "window_ms", 0)
            off = v.offset_ms or 0
            updates[f.name] = dataclasses.replace(
                v, range_selector=lp.IntervalSelector(start_ms - look - off,
                                                      end_ms - off))
        elif isinstance(v, lp.LogicalPlan):
            updates[f.name] = copy_with_time_range(v, start_ms, end_ms)
    if hasattr(plan, "start_ms"):
        updates["start_ms"] = start_ms
        updates["end_ms"] = end_ms
    return dataclasses.replace(plan, **updates)


def plan_lookback_ms(plan: lp.LogicalPlan) -> int:
    """Largest lookback/window any leaf needs (to snap split boundaries)."""
    look = 0
    for rs in lp.leaf_raw_series(plan):
        look = max(look, rs.lookback_ms or 0)
    def walk(p):
        nonlocal look
        if dataclasses.is_dataclass(p):
            look = max(look, getattr(p, "window_ms", 0) or 0)
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
    walk(plan)
    return look


# ---------------------------------------------------------------------------
# LongTimeRangePlanner
# ---------------------------------------------------------------------------


class LongTimeRangePlanner(QueryPlanner):
    """Routes to the raw cluster, the downsample cluster, or both stitched
    (reference: LongTimeRangePlanner.scala — earliestRawTime boundary;
    split point snaps to a step so the two sub-plans interleave cleanly)."""

    def __init__(self, raw_planner: QueryPlanner,
                 downsample_planner: QueryPlanner,
                 earliest_raw_time_fn: Callable[[], int],
                 latest_downsample_time_fn: Optional[Callable[[], int]] = None):
        self.raw = raw_planner
        self.downsample = downsample_planner
        self.earliest_raw_time = earliest_raw_time_fn
        self.latest_downsample_time = latest_downsample_time_fn \
            or earliest_raw_time_fn

    def materialize(self, plan: lp.LogicalPlan,
                    qctx: Optional[QueryContext] = None) -> ExecPlan:
        qctx = qctx or QueryContext()
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.raw.materialize(plan, qctx)
        start, step, end = lp.time_range(plan)
        earliest_raw = self.earliest_raw_time()
        look = plan_lookback_ms(plan)
        if start - look >= earliest_raw:
            return self.raw.materialize(plan, qctx)
        latest_ds = self.latest_downsample_time()
        if end < earliest_raw:
            return self.downsample.materialize(plan, qctx)
        # spans both: first step whose full lookback is served by raw data
        first_raw_step = start
        while first_raw_step - look < earliest_raw and first_raw_step <= end:
            first_raw_step += step
        if first_raw_step > end:
            return self.downsample.materialize(plan, qctx)
        ds_end = min(first_raw_step - step, latest_ds)
        if ds_end < start:
            return self.raw.materialize(
                copy_with_time_range(plan, first_raw_step, end), qctx)
        ds_plan = self.downsample.materialize(
            copy_with_time_range(plan, start, ds_end), qctx)
        raw_plan = self.raw.materialize(
            copy_with_time_range(plan, first_raw_step, end), qctx)
        return StitchRvsExec([ds_plan, raw_plan], qctx)


# ---------------------------------------------------------------------------
# Remote exec: PromQL over HTTP (reference: PromQlRemoteExec.scala:87)
# ---------------------------------------------------------------------------


class PromQlRemoteExec(ExecPlan):
    """Executes a PromQL string against a remote Prometheus-compatible
    endpoint and converts the JSON response back to batches."""

    def __init__(self, endpoint: str, dataset: str, promql: str,
                 start_ms: int, step_ms: int, end_ms: int,
                 query_context: Optional[QueryContext] = None,
                 timeout_s: float = 30.0):
        super().__init__(query_context)
        self.endpoint = endpoint.rstrip("/")
        self.dataset = dataset
        self.promql = promql
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.end_ms = end_ms
        self.timeout_s = timeout_s

    def _args_str(self) -> str:
        return f"endpoint={self.endpoint}, promql={self.promql!r}"

    def do_execute(self, ctx) -> list:
        import json
        import urllib.parse
        import urllib.request

        import numpy as np

        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query.model import PeriodicBatch

        qs = urllib.parse.urlencode({
            "query": self.promql,
            "start": self.start_ms / 1000.0,
            "end": self.end_ms / 1000.0,
            "step": f"{self.step_ms}ms",
        })
        url = f"{self.endpoint}/promql/{self.dataset}/api/v1/query_range?{qs}"
        # the remote hop gets min(configured cap, remaining deadline
        # budget) — never a fixed timeout (workload/deadline.py)
        from filodb_tpu.workload import deadline as dl
        deadline_timeout_s = dl.budget_timeout_s(self.query_context,
                                                 self.timeout_s)
        with urllib.request.urlopen(url,
                                    timeout=deadline_timeout_s) as resp:
            body = json.loads(resp.read())
        if body.get("status") != "success":
            raise RuntimeError(f"remote query failed: {body}")
        srange = StepRange(self.start_ms, self.end_ms, self.step_ms)
        grid = np.asarray(srange.timestamps())
        keys, rows = [], []
        for series in body["data"].get("result", ()):
            tags = dict(series["metric"])
            if "__name__" in tags:  # internal convention is _metric_
                tags["_metric_"] = tags.pop("__name__")
            vals = np.full(srange.num_steps, np.nan)
            for ts_s, v in series.get("values", ()):
                idx = np.searchsorted(grid, int(round(float(ts_s) * 1000)))
                if idx < len(grid) and grid[idx] == int(round(float(ts_s) * 1000)):
                    vals[idx] = float(v)
            keys.append(tags)
            rows.append(vals)
        if not keys:
            return []
        return [PeriodicBatch(keys, srange, np.stack(rows))]


# ---------------------------------------------------------------------------
# HighAvailabilityPlanner
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FailureTimeRange:
    """A window where local data is bad/missing (reference:
    FailureProvider.FailureTimeRange)."""

    start_ms: int
    end_ms: int
    cluster: str = "local"


class FailureProvider:
    def get_failures(self, dataset: str, start_ms: int,
                     end_ms: int) -> list[FailureTimeRange]:
        return []


class StaticFailureProvider(FailureProvider):
    def __init__(self, failures: Sequence[FailureTimeRange]):
        self.failures = list(failures)

    def get_failures(self, dataset, start_ms, end_ms):
        return [f for f in self.failures
                if f.end_ms >= start_ms and f.start_ms <= end_ms]


class MetadataRemoteExec(ExecPlan):
    """Metadata from a remote replica's Prometheus-compatible API —
    label values / series keys when the local window is failed or the
    partition is remote (reference:
    query/src/main/scala/filodb/query/exec/MetadataRemoteExec.scala:15).
    Emits the SAME batch shapes as the local LabelValuesExec /
    PartKeysExec leaves, so the metadata DistConcat mergers compose
    local and remote children transparently."""

    def __init__(self, endpoint: str, dataset: str, mode: str,
                 start_ms: int, end_ms: int,
                 label_names: Sequence[str] = (),
                 filters: Sequence = (),
                 query_context: Optional[QueryContext] = None,
                 timeout_s: float = 30.0):
        super().__init__(query_context)
        assert mode in ("labelvalues", "series")
        self.endpoint = endpoint.rstrip("/")
        self.dataset = dataset
        self.mode = mode
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.label_names = list(label_names)
        self.filters = list(filters)
        self.timeout_s = timeout_s

    def _args_str(self) -> str:
        what = self.label_names if self.mode == "labelvalues" \
            else self.filters
        return f"endpoint={self.endpoint}, mode={self.mode}, {what}"

    def _get(self, path: str, qs: dict) -> list:
        import json
        import urllib.parse
        import urllib.request

        url = (f"{self.endpoint}/promql/{self.dataset}/api/v1/{path}"
               f"?{urllib.parse.urlencode(qs, doseq=True)}")
        from filodb_tpu.workload import deadline as dl
        deadline_timeout_s = dl.budget_timeout_s(self.query_context,
                                                 self.timeout_s)
        with urllib.request.urlopen(url,
                                    timeout=deadline_timeout_s) as resp:
            body = json.loads(resp.read())
        if body.get("status") != "success":
            raise RuntimeError(f"remote metadata query failed: {body}")
        return body.get("data", [])

    def do_execute(self, ctx) -> list:
        import urllib.parse

        times = {"start": self.start_ms / 1000.0,
                 "end": self.end_ms / 1000.0}
        if self.mode == "labelvalues":
            if self.filters:
                # filters restrict the matched series (Prometheus
                # match[] on /label/<l>/values) — dropping them would
                # silently widen the failover answer
                times["match[]"] = _filters_to_promql(self.filters)
            out = {}
            for label in self.label_names:
                data = self._get(
                    f"label/{urllib.parse.quote(label)}/values", times)
                out[label] = list(data)
            return [out]
        sel = _filters_to_promql(self.filters)
        data = self._get("series", {"match[]": sel, **times})
        return [[dict(m) for m in data]]


class HighAvailabilityPlanner(QueryPlanner):
    """Routes step sub-ranges overlapping local failures to a remote
    replica via PromQL-over-HTTP, stitching local + remote results
    (reference: HighAvailabilityPlanner.scala +
    QueryFailureRoutingStrategy)."""

    def __init__(self, dataset: str, local_planner: QueryPlanner,
                 failure_provider: FailureProvider, remote_endpoint: str,
                 promql_of: Optional[Callable[[lp.LogicalPlan], str]] = None):
        self.dataset = dataset
        self.local = local_planner
        self.failures = failure_provider
        self.remote_endpoint = remote_endpoint
        self.promql_of = promql_of or logical_plan_to_promql

    def materialize(self, plan: lp.LogicalPlan,
                    qctx: Optional[QueryContext] = None) -> ExecPlan:
        qctx = qctx or QueryContext()
        if isinstance(plan, (lp.LabelValues, lp.SeriesKeysByFilters)):
            # metadata over a failed local window routes to the replica
            # wholesale (reference: MetadataRemoteExec.scala:15 — no
            # time-splitting/stitch for metadata results)
            if self.failures.get_failures(self.dataset, plan.start_ms,
                                          plan.end_ms):
                if isinstance(plan, lp.LabelValues):
                    return MetadataRemoteExec(
                        self.remote_endpoint, self.dataset, "labelvalues",
                        plan.start_ms, plan.end_ms,
                        label_names=plan.label_names,
                        filters=plan.filters, query_context=qctx)
                return MetadataRemoteExec(
                    self.remote_endpoint, self.dataset, "series",
                    plan.start_ms, plan.end_ms, filters=plan.filters,
                    query_context=qctx)
            return self.local.materialize(plan, qctx)
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.local.materialize(plan, qctx)
        start, step, end = lp.time_range(plan)
        look = plan_lookback_ms(plan)
        failures = self.failures.get_failures(self.dataset, start - look, end)
        if not failures:
            return self.local.materialize(plan, qctx)
        # A step t is bad iff some failure overlaps its lookback window
        # [t - look, t], i.e. t in [f.start, f.end + look].  Merge those
        # bad intervals and snap their boundaries to the step grid — O(F)
        # instead of O(steps * F).
        bad_ivs = sorted((f.start_ms, f.end_ms + look) for f in failures)
        merged_ivs: list[list[int]] = []
        for lo, hi in bad_ivs:
            if merged_ivs and lo <= merged_ivs[-1][1]:
                merged_ivs[-1][1] = max(merged_ivs[-1][1], hi)
            else:
                merged_ivs.append([lo, hi])

        def snap_up(t):  # first step >= t
            return start + -(-(max(t, start) - start) // step) * step

        def snap_down(t):  # last step <= t
            return start + ((min(t, end) - start) // step) * step

        segments: list[tuple[int, int, bool]] = []  # (seg_start, seg_end, bad)
        cursor = start
        for lo, hi in merged_ivs:
            bad_lo, bad_hi = snap_up(lo), snap_down(hi)
            if bad_hi < start or bad_lo > end or bad_lo > bad_hi:
                continue
            if bad_lo > cursor:
                segments.append((cursor, bad_lo - step, False))
            segments.append((bad_lo, bad_hi, True))
            cursor = bad_hi + step
        if cursor <= end:
            segments.append((cursor, end, False))

        children = []
        for seg_start, seg_end, bad in segments:
            if seg_start > seg_end:
                continue
            sub = copy_with_time_range(plan, seg_start, seg_end)
            if bad:
                children.append(PromQlRemoteExec(
                    self.remote_endpoint, self.dataset, self.promql_of(sub),
                    seg_start, step, seg_end, qctx))
            else:
                children.append(self.local.materialize(sub, qctx))
        if len(children) == 1:
            return children[0]
        return StitchRvsExec(children, qctx)


# ---------------------------------------------------------------------------
# MultiPartitionPlanner (federation)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PartitionAssignment:
    """Where one partition (installation) serves a time range (reference:
    PartitionLocationProvider.PartitionAssignment)."""

    partition_name: str
    endpoint: str
    start_ms: int
    end_ms: int


class PartitionLocationProvider:
    def get_partitions(self, shard_key_filters: dict,
                       start_ms: int, end_ms: int
                       ) -> list[PartitionAssignment]:
        raise NotImplementedError


class StaticPartitionLocations(PartitionLocationProvider):
    def __init__(self, assignments: Sequence[PartitionAssignment]):
        self.assignments = list(assignments)

    def get_partitions(self, shard_key_filters, start_ms, end_ms):
        return [a for a in self.assignments
                if a.end_ms >= start_ms and a.start_ms <= end_ms]


class MultiPartitionPlanner(QueryPlanner):
    """Federates a query across installations: the local partition plans
    locally, others become PromQL remote execs; results stitch
    (reference: MultiPartitionPlanner.scala)."""

    def __init__(self, dataset: str, local_partition: str,
                 local_planner: QueryPlanner,
                 location_provider: PartitionLocationProvider,
                 options=None,
                 promql_of: Optional[Callable[[lp.LogicalPlan], str]] = None):
        self.dataset = dataset
        self.local_partition = local_partition
        self.local = local_planner
        self.locations = location_provider
        self.options = options
        self.promql_of = promql_of or logical_plan_to_promql

    def _shard_key_filters(self, plan: lp.LogicalPlan) -> dict:
        out = {}
        for filters in lp.raw_series_filters(plan):
            for f in filters:
                if isinstance(f.filter, Equals):
                    out[f.column] = f.filter.value
        return out

    def materialize(self, plan: lp.LogicalPlan,
                    qctx: Optional[QueryContext] = None) -> ExecPlan:
        qctx = qctx or QueryContext()
        if isinstance(plan, (lp.LabelValues, lp.SeriesKeysByFilters)):
            return self._materialize_metadata(plan, qctx)
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.local.materialize(plan, qctx)
        start, step, end = lp.time_range(plan)
        look = plan_lookback_ms(plan)
        parts = self.locations.get_partitions(self._shard_key_filters(plan),
                                              start - look, end)
        if not parts:
            return EmptyResultExec(qctx)
        local_only = all(p.partition_name == self.local_partition
                        for p in parts)
        if local_only:
            return self.local.materialize(plan, qctx)
        children = []
        for p in parts:
            sub_start = max(start, p.start_ms)
            sub_end = min(end, p.end_ms)
            if sub_start > sub_end:
                continue
            # snap to the step grid
            sub_start = start + ((sub_start - start + step - 1) // step) * step
            sub_end = start + ((sub_end - start) // step) * step
            if sub_start > sub_end:
                continue
            sub = copy_with_time_range(plan, sub_start, sub_end)
            if p.partition_name == self.local_partition:
                children.append(self.local.materialize(sub, qctx))
            else:
                children.append(PromQlRemoteExec(
                    p.endpoint, self.dataset, self.promql_of(sub),
                    sub_start, step, sub_end, qctx))
        if not children:
            return EmptyResultExec(qctx)
        if len(children) == 1:
            return children[0]
        return StitchRvsExec(children, qctx)

    def _materialize_metadata(self, plan, qctx) -> ExecPlan:
        """Metadata fans out to EVERY partition — label values and
        series keys are unions, not time-splits (reference:
        MultiPartitionPlanner.scala materializeMetadataQueryPlan +
        MetadataRemoteExec.scala:15)."""
        from filodb_tpu.query.exec import (LabelValuesDistConcatExec,
                                           PartKeysDistConcatExec)
        filters = {f.column: f.filter.value for f in plan.filters
                   if isinstance(f.filter, Equals)}
        parts = self.locations.get_partitions(filters, plan.start_ms,
                                              plan.end_ms)
        if not parts:
            return EmptyResultExec(qctx)
        children: list[ExecPlan] = []
        seen: set[str] = set()
        for p in parts:
            if p.partition_name in seen:
                continue                 # one union child per partition
            seen.add(p.partition_name)
            if p.partition_name == self.local_partition:
                children.append(self.local.materialize(plan, qctx))
            elif isinstance(plan, lp.LabelValues):
                children.append(MetadataRemoteExec(
                    p.endpoint, self.dataset, "labelvalues",
                    plan.start_ms, plan.end_ms,
                    label_names=plan.label_names, filters=plan.filters,
                    query_context=qctx))
            else:
                children.append(MetadataRemoteExec(
                    p.endpoint, self.dataset, "series",
                    plan.start_ms, plan.end_ms, filters=plan.filters,
                    query_context=qctx))
        if len(children) == 1:
            return children[0]
        merger = LabelValuesDistConcatExec if isinstance(
            plan, lp.LabelValues) else PartKeysDistConcatExec
        return merger(children, qctx)


# ---------------------------------------------------------------------------
# SinglePartitionPlanner
# ---------------------------------------------------------------------------


class SinglePartitionPlanner(QueryPlanner):
    """Picks one of several planners by a selector over the plan (the
    reference keys on metric name; SinglePartitionPlanner.scala)."""

    def __init__(self, planners: dict[str, QueryPlanner],
                 planner_selector: Callable[[lp.LogicalPlan], str],
                 default: Optional[str] = None):
        self.planners = planners
        self.selector = planner_selector
        self.default = default

    def materialize(self, plan, qctx=None) -> ExecPlan:
        name = self.selector(plan)
        planner = self.planners.get(name) \
            or (self.planners[self.default] if self.default else None)
        if planner is None:
            raise ValueError(f"no planner for {name!r}")
        return planner.materialize(plan, qctx)


# ---------------------------------------------------------------------------
# ShardKeyRegexPlanner
# ---------------------------------------------------------------------------


class ShardKeyRegexPlanner(QueryPlanner):
    """Expands a regex/pipe shard-key filter (e.g. _ns_=~"App-1|App-2")
    into concrete equals filters, planning each and reducing/concatenating
    (reference: ShardKeyRegexPlanner.scala)."""

    def __init__(self, inner: QueryPlanner,
                 shard_key_matcher: Callable[[dict], list[dict]],
                 shard_key_columns: Sequence[str] = ("_ws_", "_ns_")):
        self.inner = inner
        self.matcher = shard_key_matcher  # regex key-map -> concrete key-maps
        self.shard_key_columns = tuple(shard_key_columns)

    def _regex_keys(self, plan: lp.LogicalPlan) -> Optional[dict]:
        for filters in lp.raw_series_filters(plan):
            keys = {}
            for f in filters:
                if f.column in self.shard_key_columns \
                        and isinstance(f.filter, EqualsRegex):
                    keys[f.column] = f.filter.pattern
            if keys:
                return keys
        return None

    def _replace_keys(self, plan, concrete: dict, regex: dict):
        if isinstance(plan, lp.RawSeries):
            # only rewrite the filters that actually carried THE expanded
            # regex: a leaf that pins a shard-key column with a plain Equals
            # (e.g. the other side of a binary join), or one that carries a
            # DIFFERENT regex on the same column, must keep its own selector
            new_filters = tuple(
                ColumnFilter(f.column, Equals(concrete[f.column]))
                if f.column in concrete
                and isinstance(f.filter, EqualsRegex)
                and f.filter.pattern == regex.get(f.column)
                else f
                for f in plan.filters)
            return dataclasses.replace(plan, filters=new_filters)
        if not dataclasses.is_dataclass(plan):
            return plan
        updates = {}
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, lp.LogicalPlan):
                updates[f.name] = self._replace_keys(v, concrete, regex)
        return dataclasses.replace(plan, **updates) if updates else plan

    def materialize(self, plan, qctx=None) -> ExecPlan:
        qctx = qctx or QueryContext()
        regex = self._regex_keys(plan)
        if not regex:
            return self.inner.materialize(plan, qctx)
        concretes = self.matcher(regex)
        if not concretes:
            return EmptyResultExec(qctx)
        children = [
            self.inner.materialize(self._replace_keys(plan, c, regex), qctx)
            for c in concretes]
        if len(children) == 1:
            return children[0]
        if isinstance(plan, lp.Aggregate):
            # re-reduce partial aggregates across key expansions: strip each
            # child's presenter so the reduce sees partials
            for ch in children:
                ch.transformers = [t for t in ch.transformers
                                   if not isinstance(t, AggregatePresenter)]
            red = ReduceAggregateExec(children, plan.operator, plan.params,
                                      qctx)
            red.add_transformer(AggregatePresenter(plan.operator, plan.params))
            return red
        return DistConcatExec(children, qctx)


# ---------------------------------------------------------------------------
# LogicalPlanParser: plan -> PromQL string (reference:
# LogicalPlanParser.scala round-trip)
# ---------------------------------------------------------------------------

_FN_NAME = {
    "RATE": "rate", "INCREASE": "increase", "DELTA": "delta",
    "IRATE": "irate", "IDELTA": "idelta", "DERIV": "deriv",
    "RESETS": "resets", "SUM_OVER_TIME": "sum_over_time",
    "AVG_OVER_TIME": "avg_over_time", "MIN_OVER_TIME": "min_over_time",
    "MAX_OVER_TIME": "max_over_time", "COUNT_OVER_TIME": "count_over_time",
    "STDDEV_OVER_TIME": "stddev_over_time",
    "STDVAR_OVER_TIME": "stdvar_over_time", "CHANGES": "changes",
    "QUANTILE_OVER_TIME": "quantile_over_time",
    "LAST_OVER_TIME": "last_over_time", "HOLT_WINTERS": "holt_winters",
    "PREDICT_LINEAR": "predict_linear", "ZSCORE": "z_score",
    "TIMESTAMP": "timestamp",
}


def _filters_to_promql(filters, metric_column: str = "_metric_") -> str:
    metric = ""
    matchers = []
    for f in filters:
        if f.column == metric_column and isinstance(f.filter, Equals):
            metric = f.filter.value
            continue
        flt = f.filter
        if isinstance(flt, Equals):
            matchers.append(f'{f.column}="{flt.value}"')
        elif isinstance(flt, EqualsRegex):
            matchers.append(f'{f.column}=~"{flt.pattern}"')
        elif type(flt).__name__ == "NotEquals":
            matchers.append(f'{f.column}!="{flt.value}"')
        elif type(flt).__name__ == "NotEqualsRegex":
            matchers.append(f'{f.column}!~"{flt.pattern}"')
    body = ("{" + ",".join(matchers) + "}") if matchers else ""
    return f"{metric}{body}"


def _dur(ms: int) -> str:
    if ms % 60_000 == 0 and ms:
        return f"{ms // 60_000}m"
    if ms % 1000 == 0:
        return f"{ms // 1000}s"
    return f"{ms}ms"  # never silently truncate sub-second durations


def logical_plan_to_promql(plan: lp.LogicalPlan) -> str:
    """Render a LogicalPlan back to PromQL (reference: LogicalPlanParser
    convertToQuery)."""
    if isinstance(plan, lp.PeriodicSeries):
        s = _filters_to_promql(plan.raw_series.filters)
        if plan.offset_ms:
            s += f" offset {_dur(plan.offset_ms)}"
        return s
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        fn = _FN_NAME.get(plan.function.name, plan.function.name.lower())
        inner = _filters_to_promql(plan.series.filters)
        window = f"[{_dur(plan.window_ms)}]"
        offset = f" offset {_dur(plan.offset_ms)}" if plan.offset_ms else ""
        args = "".join(f"{a}, " for a in plan.function_args)
        return f"{fn}({args}{inner}{window}{offset})"
    if isinstance(plan, lp.Aggregate):
        op = plan.operator.name.lower()
        inner = logical_plan_to_promql(plan.vectors)
        params = ", ".join(str(p) for p in plan.params)
        arg = f"{params}, {inner}" if params else inner
        suffix = ""
        if plan.by:
            suffix = f" by ({', '.join(plan.by)})"
        elif plan.without:
            suffix = f" without ({', '.join(plan.without)})"
        return f"{op}({arg}){suffix}"
    if isinstance(plan, lp.BinaryJoin):
        lhs = logical_plan_to_promql(plan.lhs)
        rhs = logical_plan_to_promql(plan.rhs)
        op = _binop_text(plan.operator)
        mods = ""
        if plan.on:
            mods = f" on ({', '.join(plan.on)})"
        elif plan.ignoring:
            mods = f" ignoring ({', '.join(plan.ignoring)})"
        b = " bool" if plan.bool_mode else ""
        return f"({lhs} {op}{b}{mods} {rhs})"
    if isinstance(plan, lp.ScalarVectorBinaryOperation):
        vec = logical_plan_to_promql(plan.vector)
        sc = logical_plan_to_promql(plan.scalar_arg)
        op = _binop_text(plan.operator)
        return f"({sc} {op} {vec})" if plan.scalar_is_lhs \
            else f"({vec} {op} {sc})"
    if isinstance(plan, lp.ApplyInstantFunction):
        fn = plan.function.name.lower()
        inner = logical_plan_to_promql(plan.vectors)
        args = "".join(f", {a}" for a in plan.function_args)
        return f"{fn}({inner}{args})"
    if isinstance(plan, lp.ApplyMiscellaneousFunction):
        fn = plan.function.name.lower()
        inner = logical_plan_to_promql(plan.vectors)
        args = "".join(f', "{a}"' for a in plan.string_args)
        return f"{fn}({inner}{args})"
    if isinstance(plan, lp.ApplySortFunction):
        return f"{plan.function.name.lower()}({logical_plan_to_promql(plan.vectors)})"
    if isinstance(plan, lp.ApplyAbsentFunction):
        return f"absent({logical_plan_to_promql(plan.vectors)})"
    if isinstance(plan, lp.ScalarFixedDoublePlan):
        return repr(plan.scalar)
    if isinstance(plan, lp.ScalarTimeBasedPlan):
        return f"{plan.function.name.lower()}()"
    if isinstance(plan, lp.ScalarVaryingDoublePlan):
        return f"scalar({logical_plan_to_promql(plan.vectors)})"
    if isinstance(plan, lp.VectorPlan):
        return f"vector({logical_plan_to_promql(plan.scalars)})"
    raise ValueError(f"cannot render {type(plan).__name__} to PromQL")


def _binop_text(op) -> str:
    return op.value  # BinaryOperator values ARE the PromQL operator text
