"""Ingest cardinality quotas: cap active series per tenant.

Capability match for the reference's CardinalityManager + QuotaSource
(reference: coordinator/.../CardinalityManager.scala — per-namespace
active-timeseries counts maintained from the part-key index, new series
over quota rejected at ingest).  Here a process-wide
:class:`SeriesQuota` is shared by every shard of a dataset and by the
gateway edge:

- the **shard** consults it in ``_get_or_add_partition_pk`` right
  before assigning a new part id: an over-quota tenant's NEW series is
  rejected (its rows dropped and counted) while existing series keep
  ingesting — a cardinality bomb saturates its own namespace only;
- the **gateway** (ShardingPublisher) consults ``over_limit`` on
  series-memo misses, shedding a bomb's container-build cost at the
  edge (advisory — the shard stays authoritative);
- counts are maintained from part-key-index lifecycle events
  (series created / removed on evict+purge) and can be rebuilt from
  the index's per-value alive refcounts (:meth:`refresh_from_index`)
  after recovery.

Metrics: ``filodb_quota_active_series{dataset,tenant}``,
``filodb_quota_limit_series``, ``filodb_quota_rejected_series_total``,
``filodb_quota_dropped_samples_total`` (see doc/workload.md).
"""

from __future__ import annotations

import threading
from typing import Mapping, Optional


def _metrics():
    from filodb_tpu.utils.observability import workload_metrics
    return workload_metrics()


class SeriesQuotaExceeded(Exception):
    """A new series would push its tenant over quota."""

    def __init__(self, tenant: str, active: int, limit: int):
        super().__init__(
            f"tenant {tenant!r} is at its active-series quota "
            f"({active}/{limit}); new series rejected")
        self.tenant = tenant
        self.active = active
        self.limit = limit


class SeriesQuota:
    """Active-series counting + limits per tenant for ONE dataset.

    The tenant key is the value of ``tenant_label`` (default the
    namespace shard-key column ``_ns_``); series without the label pool
    under ``""``.  ``default_limit=None`` means unlimited unless an
    override names the tenant."""

    def __init__(self, dataset: str = "", tenant_label: str = "_ns_",
                 default_limit: Optional[int] = None,
                 overrides: Optional[Mapping[str, int]] = None):
        self.dataset = dataset
        self.tenant_label = tenant_label
        self.default_limit = default_limit
        self.overrides = {str(k): int(v)
                          for k, v in (overrides or {}).items()}
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()
        m = _metrics()
        self._m_active = m["quota_active"]
        self._m_limit = m["quota_limit"]
        self._m_rejected = m["quota_rejected"]
        self._m_dropped = m["quota_dropped_samples"]
        for tenant, lim in self.overrides.items():
            self._m_limit.set(lim, dataset=dataset, tenant=tenant)

    # ------------------------------------------------------------------ api

    def tenant_of(self, tags: Mapping[str, str]) -> str:
        return tags.get(self.tenant_label, "")

    def limit_for(self, tenant: str) -> Optional[int]:
        lim = self.overrides.get(tenant, self.default_limit)
        return None if lim is None else int(lim)

    def active(self, tenant: str) -> int:
        with self._lock:
            return self._counts.get(tenant, 0)

    def allow_new_series(self, tags: Mapping[str, str],
                         shard: Optional[int] = None) -> bool:
        """Check-and-count for a series about to be CREATED: increments
        the tenant's active count and returns True when under quota;
        counts the rejection and returns False otherwise."""
        tenant = self.tenant_of(tags)
        lim = self.limit_for(tenant)
        with self._lock:
            n = self._counts.get(tenant, 0)
            if lim is not None and n >= lim:
                reject = True
            else:
                reject = False
                self._counts[tenant] = n + 1
        if reject:
            self._m_rejected.inc(dataset=self.dataset, tenant=tenant)
            return False
        self._m_active.set(n + 1, dataset=self.dataset, tenant=tenant)
        return True

    def over_limit(self, tags: Mapping[str, str]) -> bool:
        """Advisory read-only probe (gateway edge): would a NEW series
        of this tenant be rejected right now?"""
        tenant = self.tenant_of(tags)
        lim = self.limit_for(tenant)
        if lim is None:
            return False
        with self._lock:
            return self._counts.get(tenant, 0) >= lim

    def note_removed(self, tags: Mapping[str, str], n: int = 1) -> None:
        """Series left the index (evicted/purged): free its quota."""
        tenant = self.tenant_of(tags)
        with self._lock:
            left = self._counts.get(tenant, 0) - n
            if left <= 0:
                self._counts.pop(tenant, None)
                left = 0
            else:
                self._counts[tenant] = left
        self._m_active.set(left, dataset=self.dataset, tenant=tenant)

    def note_dropped_samples(self, tags: Mapping[str, str],
                             n: int = 1) -> None:
        self._m_dropped.inc(n, dataset=self.dataset,
                            tenant=self.tenant_of(tags))

    # ------------------------------------------------------------- lifecycle

    def configure(self, default_limit=None,
                  overrides: Optional[Mapping[str, int]] = None) -> None:
        """Runtime knob updates (POST /admin/config)."""
        if default_limit is not None:
            self.default_limit = None if int(default_limit) < 0 \
                else int(default_limit)
        if overrides is not None:
            self.overrides = {str(k): int(v) for k, v in overrides.items()}
            for tenant, lim in self.overrides.items():
                self._m_limit.set(lim, dataset=self.dataset, tenant=tenant)

    def refresh_from_index(self, *indexes) -> None:
        """Rebuild counts from part-key indexes (recovery/bootstrap):
        the per-value alive refcounts of the tenant label ARE the
        active-series counts — O(values), no document walk."""
        merged: dict[str, int] = {}
        for index in indexes:
            vc = index.value_counts(self.tenant_label)
            for value, n in vc.items():
                merged[value] = merged.get(value, 0) + n
            # series lacking the tenant label pool under ""
            untagged = len(index) - sum(vc.values())
            if untagged > 0:
                merged[""] = merged.get("", 0) + untagged
        with self._lock:
            self._counts = merged
        for tenant, n in merged.items():
            self._m_active.set(n, dataset=self.dataset, tenant=tenant)

    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
        return {"tenant_label": self.tenant_label,
                "default_limit": self.default_limit,
                "overrides": dict(self.overrides),
                "active": counts}
