"""Pre-execution query cost model, calibrated online.

Capability match for the reference's per-query resource estimation
(reference: the QuerySession/QueryConfig sample limits plus the
coordinator's plan-time shard fan-out knowledge), made quantitative so
the admission controller (workload/admission.py) can shed load BEFORE
dead work starts.

The unit of cost is a **series-chunk**: one matched series crossing one
chunk-sized window of the query's time range.  For each data leaf the
estimate is

    cost = index_hits x ceil(range / chunk_window) x op_weight

- ``index_hits`` comes from the part-key index (the same cached
  ``lookup_partitions`` walk the scan itself would do first — repeated
  dashboard shapes hit the shard's lookup cache, so estimation is a
  dict probe in steady state);
- the chunk-window count models scan volume growth with time range;
- ``op_weight`` multiplies per attached transformer (a histogram
  quantile costs more per series-chunk than a passthrough).

Leaves whose shard lives on another node (no local memstore shard)
cannot consult an index; they inherit the mean hits of the resolvable
leaves — scatter-gather children are near-uniform by construction
(spread-sharded), so this is the right prior.

**Online calibration** (ISSUE 5 tentpole): every admitted query reports
its observed wall time back via :meth:`observe`; an EWMA of
seconds-per-unit converts abstract cost into predicted seconds and a
sustainable units/second rate — the admission controller's queue-delay
estimate.  The PR 7 per-stage QueryStats timings feed this loop: the
HTTP layer observes with the query's measured total.
"""

from __future__ import annotations

import math
import threading
from typing import Optional

# default chunk window: matches the gauge StoreConfig's one-hour flush
# cadence order of magnitude but deliberately finer so short dashboards
# still see range-proportional cost
DEFAULT_CHUNK_WINDOW_MS = 600_000

# per-transformer multiplicative weights (class name -> weight); the
# absolute scale is irrelevant — calibration absorbs it — only the
# RATIOS matter for cross-query fairness
OP_WEIGHTS = {
    "PeriodicSamplesMapper": 1.0,
    "AggregateMapReduce": 1.2,
    "AggregatePresenter": 1.0,
    "InstantVectorFunctionMapper": 1.1,
    "HistogramQuantileMapper": 2.5,
    "ScalarOperationMapper": 1.05,
    "SortFunctionMapper": 1.1,
    "AbsentFunctionMapper": 1.05,
    "MiscellaneousFunctionMapper": 1.1,
    "VectorFunctionMapper": 1.0,
    "StitchRvsMapper": 1.1,
}

# heavy range functions pay extra per series-chunk
RANGE_FN_WEIGHTS = {
    "HOLT_WINTERS": 2.0,
    "PREDICT_LINEAR": 1.5,
    "QUANTILE_OVER_TIME": 2.0,
    "MAD_OVER_TIME": 2.0,
}

_DEFAULT_HITS = 8.0  # prior for an unresolvable (remote) leaf


class CostModel:
    """Estimates cost units per ExecPlan and calibrates units->seconds."""

    def __init__(self, chunk_window_ms: int = DEFAULT_CHUNK_WINDOW_MS,
                 sec_per_unit: float = 2e-5, alpha: float = 0.2):
        self.chunk_window_ms = max(int(chunk_window_ms), 1)
        # EWMA state: seconds one cost unit takes on THIS node, seeded
        # with a deliberately optimistic prior so cold admission never
        # sheds; a few observed queries converge it
        self._sec_per_unit = float(sec_per_unit)
        self._alpha = float(alpha)
        self._observed = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ estimation

    def estimate(self, plan, memstore=None) -> float:
        """Cost units for an ExecPlan tree (>= 1.0 always — even a
        metadata query occupies a worker)."""
        leaves: list[tuple[object, Optional[float]]] = []
        self._collect(plan, memstore, leaves)
        resolved = [h for _p, h in leaves if h is not None]
        fallback = (sum(resolved) / len(resolved)) if resolved \
            else _DEFAULT_HITS
        total = 0.0
        for leaf, hits in leaves:
            h = hits if hits is not None else fallback
            total += h * self._chunks(leaf) * self._weight(leaf)
        return max(total, 1.0)

    def estimate_seconds(self, cost: float) -> float:
        return cost * self._sec_per_unit

    def units_per_second(self) -> float:
        return 1.0 / self._sec_per_unit

    @property
    def observations(self) -> int:
        return self._observed

    # ------------------------------------------------------------ calibration

    def observe(self, cost: float, seconds: float) -> None:
        """Fold one completed query's (estimated cost, measured wall
        seconds) into the EWMA; drives units_per_second toward the
        node's real throughput.

        UPWARD moves are rate-limited to 4x per observation: shed
        queries never observe, so a single compile-inflated cold-start
        sample that overshoots the shed threshold could otherwise wedge
        admission into rejecting a whole traffic class with nothing
        left to pull the estimate back down.  A genuinely slow node
        still converges geometrically; downward (faster-than-believed)
        moves are unrestricted."""
        if cost <= 0 or seconds < 0:
            return
        obs = seconds / cost
        with self._lock:
            prev = self._sec_per_unit
            if self._observed == 0:
                nxt = obs
            else:
                nxt = prev + self._alpha * (obs - prev)
            self._sec_per_unit = min(nxt, prev * 4.0)
            self._observed += 1

    # -------------------------------------------------------------- internals

    def _collect(self, plan, memstore, out: list) -> None:
        """Walk the exec tree collecting (leaf, index_hits|None)."""
        shard = getattr(plan, "shard", None)
        filters = getattr(plan, "filters", None)
        if filters is not None and isinstance(shard, int):
            out.append((plan, self._leaf_hits(plan, shard, memstore)))
            return
        shards = getattr(plan, "shards", None)
        if filters is not None and isinstance(shards, (list, tuple)):
            # mesh-fused local multi-shard leaf: sum per-shard hits
            hits = [self._leaf_hits(plan, s, memstore) for s in shards]
            known = [h for h in hits if h is not None]
            out.append((plan, sum(known) if known else None))
            return
        for child in getattr(plan, "children", ()) or ():
            self._collect(child, memstore, out)

    @staticmethod
    def _leaf_hits(plan, shard: int, memstore) -> Optional[float]:
        if memstore is None:
            return None
        try:
            sh = memstore.get_shard(plan.dataset, shard)
            lookup = sh.lookup_partitions(list(plan.filters), plan.start_ms,
                                          plan.end_ms)
            return float(len(lookup.part_ids) + len(lookup.missing_partkeys))
        except Exception:  # noqa: BLE001 — remote/unreachable shard
            return None

    def _chunks(self, leaf) -> float:
        start = getattr(leaf, "start_ms", 0)
        end = getattr(leaf, "end_ms", 0)
        return float(max(1, math.ceil(max(end - start, 0)
                                      / self.chunk_window_ms)))

    @staticmethod
    def _weight(leaf) -> float:
        w = 1.0
        for t in getattr(leaf, "transformers", ()):
            w *= OP_WEIGHTS.get(type(t).__name__, 1.0)
            fn = getattr(t, "function", None)
            name = getattr(fn, "name", None)
            if name in RANGE_FN_WEIGHTS:
                w *= RANGE_FN_WEIGHTS[name]
        return w
