"""Cost-based admission control: shed load BEFORE it queues to death.

Capability match for the reference's overload defenses (reference:
QueryActor's bounded priority mailbox + queryTimeoutMillis relinquish,
and the cluster's per-namespace QuotaSource) combined into one front
door: every query the HTTP layer is about to schedule first passes
``AdmissionController.admit``, which knows

- the query's **estimated cost** (workload/cost.py) and **remaining
  deadline budget** (workload/deadline.py),
- the node's **calibrated throughput** (cost units/second x workers),
- what is already **in flight** globally, per tenant, and per priority
  class.

A query is shed with HTTP 429 + ``Retry-After`` (never queued to rot)
when any of these hold:

- its deadline already expired (reason ``expired``);
- the estimated queue delay — inflight cost over calibrated throughput —
  exceeds the remaining budget (reason ``deadline``): executing it
  would be dead work by construction;
- admitting it would push inflight cost past its priority class's
  ceiling (reason ``overload``).  Ceilings are FRACTIONS of the global
  budget ({low: 0.5, default: 0.8, high: 1.0} by default), so bulk/
  dashboard traffic saturates at 80% and interactive high-priority
  queries always find reserved headroom — the bounded-p50 guarantee the
  overload e2e test asserts;
- the tenant is over its concurrent-query or inflight-cost budget
  (reasons ``tenant_concurrency`` / ``tenant_cost``): one tenant's
  scatter-gather storm cannot starve the rest.

``admit`` returns a context-manager permit; releasing it feeds the
measured wall time back into the cost model's calibration loop.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Optional

from filodb_tpu.query.model import QueryContext
from filodb_tpu.query.scheduler import QueryRejected
from filodb_tpu.workload import deadline as dl
from filodb_tpu.workload.cost import CostModel

DEFAULT_PRIORITY_SHARES = {"low": 0.5, "default": 0.8, "high": 1.0,
                           # the rule engine's dedicated class (ISSUE 9):
                           # BELOW "low", so a pathological rule group
                           # saturates at 40% of the budget and can
                           # never starve interactive traffic
                           "rules": 0.4,
                           # the rollup scheduler's class (ISSUE 11):
                           # below even "rules" — tiering is the most
                           # deferrable work in the system (a deferred
                           # tick just retries; closure semantics make
                           # catch-up lossless)
                           "rollup": 0.3}


class AdmissionRejected(QueryRejected):
    """Shed by admission control: the HTTP layer maps this to
    429 Too Many Requests with a ``Retry-After`` hint."""

    def __init__(self, query_id: str, message: str, reason: str,
                 retry_after_s: float = 1.0):
        super().__init__(query_id, message)
        self.reason = reason
        self.retry_after_s = max(float(retry_after_s), 1.0)


def _metrics():
    from filodb_tpu.utils.observability import workload_metrics
    return workload_metrics()


class AdmissionController:
    """Per-dataset admission front door (one per DatasetBinding)."""

    def __init__(self, cost_model: Optional[CostModel] = None,
                 dataset: str = "",
                 max_inflight_cost: float = 10_000.0,
                 priority_shares: Optional[dict] = None,
                 tenant_max_concurrent: int = 32,
                 tenant_max_inflight_cost: Optional[float] = None,
                 workers: int = 4,
                 enabled: bool = True):
        self.cost_model = cost_model or CostModel()
        self.dataset = dataset
        self.max_inflight_cost = float(max_inflight_cost)
        # partial configs MERGE over the defaults: a shares dict naming
        # only {"high": 1.0} must not strip the "default" class every
        # unlabelled query lands in
        self.priority_shares = dict(DEFAULT_PRIORITY_SHARES)
        self.priority_shares.update(priority_shares or {})
        self.tenant_max_concurrent = int(tenant_max_concurrent)
        self.tenant_max_inflight_cost = tenant_max_inflight_cost
        self.workers = max(int(workers), 1)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._inflight_cost = 0.0
        self._inflight_queries = 0
        self._tenant_cost: dict[str, float] = {}
        self._tenant_running: dict[str, int] = {}
        m = _metrics()
        self._m_admitted = m["admitted"]
        self._m_rejected = m["rejected"]
        self._m_inflight = m["inflight_cost"]
        self._m_est = m["estimated_cost"]
        self._m_inflight.set_fn(lambda: self._inflight_cost,
                                dataset=dataset)

    # ------------------------------------------------------------- lifecycle

    def configure(self, max_inflight_cost=None, tenant_max_concurrent=None,
                  tenant_max_inflight_cost=None, enabled=None) -> None:
        """Runtime knob updates (POST /admin/config)."""
        if max_inflight_cost is not None:
            self.max_inflight_cost = float(max_inflight_cost)
        if tenant_max_concurrent is not None:
            self.tenant_max_concurrent = int(tenant_max_concurrent)
        if tenant_max_inflight_cost is not None:
            self.tenant_max_inflight_cost = float(tenant_max_inflight_cost)
        if enabled is not None:
            self.enabled = bool(enabled)

    def shutdown(self) -> None:
        self._m_inflight.remove(dataset=self.dataset)

    # -------------------------------------------------------------- admission

    def queue_delay_est_s(self, extra_cost: float = 0.0) -> float:
        """Expected wait before ``extra_cost`` units would COMPLETE,
        given what is already in flight and the calibrated rate."""
        rate = self.cost_model.units_per_second() * self.workers
        return (self._inflight_cost + extra_cost) / max(rate, 1e-9)

    def admit(self, qctx: QueryContext, cost: float):
        """Admit or raise :class:`AdmissionRejected`.  Returns a context
        manager releasing the budget and calibrating the cost model."""
        if not self.enabled:
            return contextlib.nullcontext()
        cost = max(float(cost), 1.0)
        self._m_est.observe(cost, dataset=self.dataset)
        tenant = qctx.tenant or "default"
        priority = qctx.priority or "default"
        share = self.priority_shares.get(priority)
        if share is None:  # unknown class -> the default class's share
            share = self.priority_shares.get("default", 1.0)
        rem_ms = dl.remaining_ms(qctx)
        with self._lock:
            if rem_ms is not None and rem_ms <= 0:
                self._reject(qctx, tenant, priority, "expired", 1.0,
                             f"deadline expired {-rem_ms}ms ago on arrival")
            est_delay_s = self.queue_delay_est_s(cost)
            if rem_ms is not None and est_delay_s * 1000.0 > rem_ms:
                self._reject(
                    qctx, tenant, priority, "deadline",
                    math.ceil(est_delay_s),
                    f"estimated queue delay {est_delay_s * 1000:.0f}ms "
                    f"exceeds the {rem_ms}ms deadline budget left")
            ceiling = share * self.max_inflight_cost
            if self._inflight_cost + cost > ceiling:
                over = self._inflight_cost + cost - ceiling
                rate = self.cost_model.units_per_second() * self.workers
                self._reject(
                    qctx, tenant, priority, "overload",
                    math.ceil(over / max(rate, 1e-9)),
                    f"inflight cost {self._inflight_cost:.0f} + "
                    f"{cost:.0f} exceeds the {priority!r} ceiling "
                    f"{ceiling:.0f} (of {self.max_inflight_cost:.0f})")
            if self._tenant_running.get(tenant, 0) \
                    >= self.tenant_max_concurrent:
                self._reject(
                    qctx, tenant, priority, "tenant_concurrency",
                    math.ceil(self.queue_delay_est_s()
                              / self.tenant_max_concurrent) or 1,
                    f"tenant {tenant!r} already runs "
                    f"{self.tenant_max_concurrent} concurrent queries")
            tcost = self._tenant_cost.get(tenant, 0.0)
            if self.tenant_max_inflight_cost is not None \
                    and tcost + cost > self.tenant_max_inflight_cost:
                self._reject(
                    qctx, tenant, priority, "tenant_cost", 1.0,
                    f"tenant {tenant!r} inflight cost {tcost:.0f} + "
                    f"{cost:.0f} exceeds its budget "
                    f"{self.tenant_max_inflight_cost:.0f}")
            self._inflight_cost += cost
            self._inflight_queries += 1
            self._tenant_cost[tenant] = tcost + cost
            self._tenant_running[tenant] = \
                self._tenant_running.get(tenant, 0) + 1
        self._m_admitted.inc(dataset=self.dataset, priority=priority)
        return _Permit(self, tenant, cost, qctx)

    def _reject(self, qctx, tenant, priority, reason, retry_after_s,
                detail) -> None:
        self._m_rejected.inc(dataset=self.dataset, priority=priority,
                             reason=reason)
        raise AdmissionRejected(
            qctx.query_id,
            f"query shed by admission control ({reason}): {detail}",
            reason, retry_after_s)

    def _release(self, tenant: str, cost: float, seconds: float) -> None:
        with self._lock:
            self._inflight_cost = max(self._inflight_cost - cost, 0.0)
            self._inflight_queries = max(self._inflight_queries - 1, 0)
            left = self._tenant_cost.get(tenant, 0.0) - cost
            if left <= 1e-9:
                self._tenant_cost.pop(tenant, None)
            else:
                self._tenant_cost[tenant] = left
            n = self._tenant_running.get(tenant, 0) - 1
            if n <= 0:
                self._tenant_running.pop(tenant, None)
            else:
                self._tenant_running[tenant] = n
        self.cost_model.observe(cost, seconds)

    # ----------------------------------------------------------------- admin

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "max_inflight_cost": self.max_inflight_cost,
                "priority_shares": dict(self.priority_shares),
                "tenant_max_concurrent": self.tenant_max_concurrent,
                "tenant_max_inflight_cost": self.tenant_max_inflight_cost,
                "inflight_cost": self._inflight_cost,
                "inflight_queries": self._inflight_queries,
                "tenant_inflight_cost": dict(self._tenant_cost),
                "tenant_running": dict(self._tenant_running),
                "sec_per_unit": 1.0 / self.cost_model.units_per_second(),
                "calibration_observations": self.cost_model.observations,
            }


class _Permit:
    """Releases admitted budget on exit and calibrates the cost model
    with the measured wall time.

    While held, the permit is stamped onto the query's
    ``QueryContext.admission_permit`` (fleet batching tier, ISSUE 20):
    a batch leader re-checks ``released`` at stack time, so a query
    whose admission window closed mid-batch is dropped from the stack
    instead of executing outside it."""

    def __init__(self, ctrl: AdmissionController, tenant: str, cost: float,
                 qctx: Optional[QueryContext] = None):
        self._ctrl = ctrl
        self._tenant = tenant
        self.cost = cost
        self._t0 = 0.0
        self._qctx = qctx
        self.released = False

    def __enter__(self):
        self._t0 = time.perf_counter()
        if self._qctx is not None:
            self._qctx.admission_permit = self
        return self

    def __exit__(self, exc_type, exc, tb):
        self.released = True
        if self._qctx is not None \
                and self._qctx.admission_permit is self:
            self._qctx.admission_permit = None
        self._ctrl._release(self._tenant, self.cost,
                            time.perf_counter() - self._t0)
        return False


def tenant_of(filters, shard_key_columns=("_ws_", "_ns_")) -> str:
    """Derive the tenant identity from a query's shard-key equality
    filters (the reference keys its quotas the same way: workspace/
    namespace).  Empty string when the query names no tenant."""
    from filodb_tpu.core.filters import equals_value
    parts = []
    for col in shard_key_columns:
        v = equals_value(list(filters), col)
        if v is not None:
            parts.append(v)
    return "/".join(parts)


def plan_tenant(plan) -> str:
    """Tenant of a logical/exec plan tree: the first leaf carrying
    shard-key filters decides (scatter-gather children share them)."""
    filters = getattr(plan, "filters", None)
    if filters:
        t = tenant_of(filters)
        if t:
            return t
    for attr in ("children", ):
        for child in getattr(plan, attr, ()) or ():
            t = plan_tenant(child)
            if t:
                return t
    for attr in ("vectors", "series", "raw_series", "lhs", "rhs"):
        child = getattr(plan, attr, None)
        if child is not None and not isinstance(child, (int, float)):
            t = plan_tenant(child)
            if t:
                return t
    return ""
