"""End-to-end query deadlines: one wall-clock budget, decremented per hop.

Capability match for the reference's query-timeout plumbing (reference:
QueryContext.submitTime + queryTimeoutMillis checked in QueryActor's
mailbox and again inside ExecPlan execution) extended the way
scale-out serving fabrics do it: the HTTP entry point mints an ABSOLUTE
deadline (``QueryContext.deadline_ms``, epoch millis) from the query's
timeout; every layer that waits or ships work derives its own timeout
from the REMAINING budget instead of a fixed constant.  Across the
``/execplan`` wire the budget travels as a relative ``budget_ms`` (wall
clocks differ between nodes; see query/wire.py), so the receiving node
re-anchors it against its own clock and can refuse work that cannot
finish in time.

All helpers degrade to "no deadline" (``None``) when the context never
minted one (``deadline_ms == 0``) so library callers and old tests keep
their unbounded behavior.
"""

from __future__ import annotations

import time
from typing import Optional

from filodb_tpu.query.model import QueryContext, QueryError

# a remote hop that has less budget than this cannot plausibly finish:
# the data node refuses it outright instead of starting dead work
MIN_REMOTE_BUDGET_MS = 5


class DeadlineExceeded(QueryError):
    """The query's end-to-end budget ran out before the work finished
    (or could even start)."""


def mint(qctx: QueryContext, now_ms: Optional[int] = None) -> QueryContext:
    """Stamp an absolute deadline onto a context that lacks one:
    ``submit_time_ms + timeout_ms`` (the HTTP entry point calls this
    once; everything downstream only ever reads/decrements)."""
    if not qctx.deadline_ms:
        base = qctx.submit_time_ms or (now_ms if now_ms is not None
                                       else int(time.time() * 1000))
        qctx.deadline_ms = base + qctx.timeout_ms
    return qctx


def remaining_ms(qctx: QueryContext,
                 now_ms: Optional[int] = None) -> Optional[int]:
    """Milliseconds of budget left; ``None`` when no deadline was
    minted; can be negative (already expired)."""
    if not qctx.deadline_ms:
        return None
    now = now_ms if now_ms is not None else int(time.time() * 1000)
    return qctx.deadline_ms - now


def expired(qctx: QueryContext, now_ms: Optional[int] = None) -> bool:
    rem = remaining_ms(qctx, now_ms)
    return rem is not None and rem <= 0


def check(qctx: QueryContext, where: str = "") -> None:
    """Raise :class:`DeadlineExceeded` when the budget ran out — the
    cheap per-hop tripwire (one clock read)."""
    rem = remaining_ms(qctx)
    if rem is not None and rem <= 0:
        raise DeadlineExceeded(
            qctx.query_id,
            f"query deadline exceeded ({-rem}ms past its "
            f"{qctx.timeout_ms}ms budget{f' at {where}' if where else ''})")


def budget_timeout_s(qctx: QueryContext, cap_s: float) -> float:
    """A wait/IO timeout capped by the remaining budget: the fix for the
    fixed-60s dispatch timeout (ISSUE 5 satellite #1).  Returns ``cap_s``
    when no deadline exists, else ``min(cap, remaining)`` floored at a
    millisecond so an expired budget fails fast instead of waiting 0s
    forever (urllib treats 0 as no timeout)."""
    rem = remaining_ms(qctx)
    if rem is None:
        return cap_s
    return min(cap_s, max(rem / 1000.0, 0.001))
