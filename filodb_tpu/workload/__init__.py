"""Workload management: cost-based admission, tenant quotas, deadlines.

ISSUE 5's tentpole — the overload defenses a multi-tenant serving node
needs before scale-out pays off (see doc/workload.md):

- :mod:`filodb_tpu.workload.cost` — pre-execution cost estimates per
  ExecPlan, calibrated online from observed query wall time;
- :mod:`filodb_tpu.workload.admission` — per-tenant / per-priority
  budgets in front of the query scheduler; sheds with 429 + Retry-After;
- :mod:`filodb_tpu.workload.deadline` — one wall-clock budget minted at
  the HTTP entry, decremented at every hop, capping every dispatch
  timeout, refusing dead work;
- :mod:`filodb_tpu.workload.quota` — active-series cardinality quotas
  per tenant, enforced at series creation and shed at the gateway edge.
"""

from filodb_tpu.workload.admission import (AdmissionController,  # noqa: F401
                                           AdmissionRejected)
from filodb_tpu.workload.cost import CostModel  # noqa: F401
from filodb_tpu.workload.deadline import (DeadlineExceeded,  # noqa: F401
                                          MIN_REMOTE_BUDGET_MS)
from filodb_tpu.workload.quota import (SeriesQuota,  # noqa: F401
                                       SeriesQuotaExceeded)
