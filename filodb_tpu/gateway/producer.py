"""Test time-series load generators.

Capability match for the reference's TestTimeseriesProducer (reference:
gateway/src/main/scala/filodb/timeseries/TestTimeseriesProducer.scala:25
— generates prom-schema gauge/counter/histogram load with the canonical
tag structure: metric + _ws_/_ns_ shard keys, dc/partition/host/instance
spread tags) and the CSV ingestion source (reference:
coordinator/.../sources/CsvStream.scala:16).
"""

from __future__ import annotations

import csv
import io
from typing import Iterator, Optional, Sequence

import numpy as np

from filodb_tpu.core.histogram import GeometricBuckets
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import Schemas
from filodb_tpu.codecs import histcodec
from filodb_tpu.ingest.stream import ListStreamFactory, StreamElement


def series_tags(metric: str, i: int, ws: str = "demo",
                app_groups: int = 8) -> dict[str, str]:
    """The reference's tag shape: dc/partition/host/instance cycle at
    different rates so cardinality multiplies (reference:
    TestTimeseriesProducer.tagsForInstance)."""
    return {"__name__": metric, "_ws_": ws, "_ns_": f"App-{i % app_groups}",
            "dc": f"DC{i % 2}", "partition": f"partition-{i % 4}",
            "host": f"H{i % 10}", "instance": f"Instance-{i}"}


class TestTimeseriesProducer:
    """Deterministic prom-schema load generator."""

    __test__ = False  # not a pytest class, despite the reference's name

    def __init__(self, schemas: Schemas, seed: int = 0,
                 start_ms: int = 1_700_000_000_000, interval_ms: int = 10_000):
        self.schemas = schemas
        self.rng = np.random.default_rng(seed)
        self.start_ms = start_ms
        self.interval_ms = interval_ms

    def gauge_containers(self, metric: str = "heap_usage", n_series: int = 100,
                         n_samples: int = 100,
                         container_size: int = 1024 * 1024) -> list[bytes]:
        b = RecordBuilder(self.schemas["gauge"], container_size=container_size)
        for i in range(n_series):
            tags = series_tags(metric, i)
            vals = 50 + 15 * np.sin(np.arange(n_samples) / 10 + i) \
                + self.rng.random(n_samples)
            for k in range(n_samples):
                b.add(self.start_ms + k * self.interval_ms,
                      [float(vals[k])], tags)
        return b.containers()

    def counter_containers(self, metric: str = "requests_total",
                           n_series: int = 100, n_samples: int = 100,
                           container_size: int = 1024 * 1024) -> list[bytes]:
        b = RecordBuilder(self.schemas["prom-counter"],
                          container_size=container_size)
        for i in range(n_series):
            tags = series_tags(metric, i)
            vals = np.cumsum(self.rng.random(n_samples) * 10)
            for k in range(n_samples):
                b.add(self.start_ms + k * self.interval_ms,
                      [float(vals[k])], tags)
        return b.containers()

    def histogram_containers(self, metric: str = "request_latency",
                             n_series: int = 20, n_samples: int = 50,
                             num_buckets: int = 8,
                             container_size: int = 1024 * 1024) -> list[bytes]:
        b = RecordBuilder(self.schemas["prom-histogram"],
                          container_size=container_size)
        buckets = GeometricBuckets(2.0, 2.0, num_buckets)
        for i in range(n_series):
            tags = series_tags(metric, i)
            counts = np.zeros(num_buckets, dtype=np.int64)
            total = 0.0
            for k in range(n_samples):
                inc = self.rng.integers(0, 10, num_buckets)
                counts = counts + np.cumsum(inc)  # cumulative LE buckets
                total += float(inc.sum() * 1.5)
                blob = histcodec.encode_hist_value(buckets, counts)
                b.add(self.start_ms + k * self.interval_ms,
                      [total, float(counts[-1]), blob], tags)
        return b.containers()

    def influx_lines(self, metric: str = "cpu_usage", n_series: int = 10,
                     n_samples: int = 20) -> list[str]:
        """Influx line-protocol rendering of a gauge load (for gateway
        tests)."""
        lines = []
        for i in range(n_series):
            tags = series_tags(metric, i)
            name = tags.pop("__name__")
            tag_str = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
            for k in range(n_samples):
                ts_ns = (self.start_ms + k * self.interval_ms) * 1_000_000
                val = 50 + i + k * 0.5
                lines.append(f"{name},{tag_str} value={val} {ts_ns}")
        return lines


# ---------------------------------------------------------------------------
# CSV ingestion source
# ---------------------------------------------------------------------------


def csv_stream_elements(text: str, schemas: Schemas, schema_name: str,
                        tag_columns: Sequence[str],
                        timestamp_column: str = "timestamp",
                        value_columns: Optional[Sequence[str]] = None,
                        container_size: int = 64 * 1024
                        ) -> list[StreamElement]:
    """CSV -> (offset, container) stream elements (reference: CsvStream —
    deterministic source used by cluster recovery specs).

    Columns: ``timestamp_column`` (epoch ms), ``value_columns`` (defaults
    to the schema's data columns), everything in ``tag_columns`` becomes a
    tag."""
    schema = schemas[schema_name]
    if value_columns is None:
        value_columns = [c.name for c in schema.data.columns[1:]]
    builder = RecordBuilder(schema, container_size=container_size)
    reader = csv.DictReader(io.StringIO(text))
    for row in reader:
        tags = {t: row[t] for t in tag_columns if row.get(t)}
        values = [float(row[v]) for v in value_columns]
        builder.add(int(row[timestamp_column]), values, tags)
    return list(enumerate(builder.containers()))


def csv_source_factory(path: str, schemas: Schemas, schema_name: str,
                       tag_columns: Sequence[str],
                       shard: int = 0, **kwargs) -> ListStreamFactory:
    with open(path) as f:
        elements = csv_stream_elements(f.read(), schemas, schema_name,
                                       tag_columns, **kwargs)
    return ListStreamFactory({shard: elements})
