"""Self-telemetry: the node scrapes its own /metrics into a dataset.

The third pillar of the data-plane observability layer (ISSUE 6): every
``interval_s`` the node parses its own Prometheus exposition
(``REGISTRY.expose_text`` — byte-identical to what ``GET /metrics``
serves) and publishes each sample through the EXISTING gateway ingest
path (``ShardingPublisher.add_sample`` -> record containers -> the
dataset's ingest stream), landing in a Prometheus-schema dataset
(default ``_system``).  Operators then ask node-health questions in
plain PromQL through the normal query path::

    rate(filodb_selfscrape_samples_total{_ws_="filodb"}[1m])
    filodb_ingest_lag_rows{dataset="prom"}

This is the dogfooding substrate recording rules (ROADMAP 3) and HA
health routing (ROADMAP 4) will evaluate against — a queryable stream
of the node's own metrics, not just a scrape endpoint.

The parser handles the exposition grammar our registry emits (and
Prometheus' escaping rules: ``\\\\``, ``\\"``, ``\\n`` in label
values); non-finite samples are skipped (a NaN/Inf gauge has no sample
representation worth storing).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Iterator, Mapping, Optional

_METRICS = None


def _m() -> dict:
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import selfscrape_metrics
        _METRICS = selfscrape_metrics()
    return _METRICS


def _parse_value(tok: str) -> float:
    if tok == "+Inf":
        return math.inf
    if tok == "-Inf":
        return -math.inf
    return float(tok)  # float() accepts "NaN"


def _parse_labels(text: str) -> dict[str, str]:
    """``k="v",k2="v2"`` with Prometheus escaping inside the quotes."""
    labels: dict[str, str] = {}
    i = 0
    n = len(text)
    while i < n:
        eq = text.index("=", i)
        key = text[i:eq].strip().lstrip(",").strip()
        i = eq + 1
        if i >= n or text[i] != '"':
            raise ValueError(f"unquoted label value in {text!r}")
        i += 1
        out = []
        while i < n:
            c = text[i]
            if c == "\\" and i + 1 < n:
                nxt = text[i + 1]
                out.append({"n": "\n"}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                break
            out.append(c)
            i += 1
        labels[key] = "".join(out)
        i += 1  # past the closing quote
    return labels


def parse_exposition(text: str) -> Iterator[tuple[str, dict, float]]:
    """Prometheus text exposition -> ``(name, labels, value)`` samples.
    Comment/TYPE/HELP lines are skipped; malformed lines raise (the
    scraper counts and drops the pass — our own exposition is tested
    against the grammar, so a parse failure is a bug worth seeing)."""
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        sp = line.find(" ")
        if 0 <= brace < sp or (brace >= 0 and sp < 0):
            close = line.rindex("}")
            name = line[:brace]
            labels = _parse_labels(line[brace + 1:close]) \
                if close > brace + 1 else {}
            rest = line[close + 1:].strip()
        else:
            name = line[:sp]
            labels = {}
            rest = line[sp + 1:].strip()
        value = _parse_value(rest.split()[0])
        yield name, labels, value


class SelfScraper:
    """Background scrape loop: exposition -> gateway publisher.

    ``default_tags`` ride every sample (shard-key columns so PromQL can
    select the node's telemetry: ``_ws_="filodb"``, ``_ns_=<node>``,
    ``instance=<node>`` by convention); exposition labels win on
    collision so metric semantics (e.g. ``dataset=``) survive."""

    def __init__(self, publisher, interval_s: float = 10.0,
                 expose_fn: Optional[Callable[[], str]] = None,
                 default_tags: Optional[Mapping[str, str]] = None):
        if expose_fn is None:
            from filodb_tpu.utils.observability import REGISTRY
            expose_fn = REGISTRY.expose_text
        from filodb_tpu.utils.observability import PeriodicThread
        self.publisher = publisher
        self.interval_s = float(interval_s)
        self.expose_fn = expose_fn
        self.default_tags = dict(default_tags or {})
        self._loop = PeriodicThread(self.scrape_once, self.interval_s,
                                    "self-scrape")

    def scrape_once(self) -> int:
        """One pass: parse the exposition, publish every finite sample
        at 'now', flush the containers.  Returns samples published."""
        m = _m()
        t0 = time.perf_counter()
        now_ms = int(time.time() * 1000)
        n = 0
        try:
            text = self.expose_fn()
            for name, labels, value in parse_exposition(text):
                if not math.isfinite(value):
                    continue
                tags = dict(self.default_tags)
                tags.update(labels)
                self.publisher.add_sample(name, tags, now_ms, value)
                n += 1
            self.publisher.flush()
        except Exception:  # noqa: BLE001 — telemetry never kills the node
            m["errors"].inc()
            raise
        finally:
            m["duration"].set(time.perf_counter() - t0)
        m["scrapes"].inc()
        if n:
            m["samples"].inc(n)
        return n

    def start(self) -> None:
        self._loop.start()

    def stop(self) -> None:
        self._loop.stop()
