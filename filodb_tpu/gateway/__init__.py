"""Metrics gateway: Influx line-protocol edge, sharding publisher, load
generators (reference: gateway/ module)."""

from filodb_tpu.gateway.influx import InfluxRecord, parse_line, parse_lines  # noqa: F401
from filodb_tpu.gateway.producer import (  # noqa: F401
    TestTimeseriesProducer, csv_stream_elements, series_tags)
from filodb_tpu.gateway.server import GatewayServer, ShardingPublisher  # noqa: F401
