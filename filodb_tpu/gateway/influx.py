"""Influx line protocol parser -> input records.

Capability match for the reference's gateway conversion layer (reference:
gateway/src/main/scala/filodb/gateway/conversion/
InfluxProtocolParser.scala:65, InfluxRecord.scala — parse
``measurement,tag=v,... field=1.0,... <ts>`` lines; single-field records
map to the gauge/counter prom schemas, ``sum``/``count``/bucket fields
map to histograms; InputRecord.scala:15 defines the conversion target).

Escapes per the Influx spec: ``\\,`` ``\\ `` ``\\=`` in identifiers/tags,
``\\"`` in string field values.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Mapping, Optional


class InfluxParseError(ValueError):
    pass


@dataclasses.dataclass
class InfluxRecord:
    """One parsed line (reference: InfluxPromSingleRecord /
    InfluxHistogramRecord)."""

    measurement: str
    tags: dict[str, str]
    fields: dict[str, float]
    timestamp_ms: int

    def kind(self) -> str:
        """gauge | histogram — histogram when bucket-style fields present
        (reference: InfluxProtocolParser.record: histogram chosen when
        fields are sum/count/+Inf/le buckets)."""
        names = set(self.fields)
        if "sum" in names and "count" in names and len(names) > 2:
            return "histogram"
        return "gauge"


def _split_escaped(text: str, sep: str) -> list[str]:
    """Split on unescaped sep, PRESERVING escape sequences in the pieces
    (so later splits on '=' still see which ones were escaped)."""
    out, cur, i = [], [], 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            cur.append(text[i:i + 2])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unescape(text: str) -> str:
    out, i = [], 0
    while i < len(text):
        if text[i] == "\\" and i + 1 < len(text):
            out.append(text[i + 1])
            i += 2
        else:
            out.append(text[i])
            i += 1
    return "".join(out)


def _find_unescaped(text: str, ch: str, start: int = 0) -> int:
    i = start
    while i < len(text):
        if text[i] == "\\":
            i += 2
            continue
        if text[i] == ch:
            return i
        i += 1
    return -1


def _find_outside_quotes(text: str, ch: str) -> int:
    """First unescaped ``ch`` that is not inside a double-quoted string
    (field values may contain spaces/commas in quotes)."""
    i = 0
    in_quotes = False
    while i < len(text):
        c = text[i]
        if c == "\\":
            i += 2
            continue
        if c == '"':
            in_quotes = not in_quotes
        elif c == ch and not in_quotes:
            return i
        i += 1
    return -1


def _split_outside_quotes(text: str, sep: str) -> list[str]:
    out, cur, i = [], [], 0
    in_quotes = False
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            cur.append(text[i:i + 2])
            i += 2
            continue
        if c == '"':
            in_quotes = not in_quotes
            cur.append(c)
        elif c == sep and not in_quotes:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def parse_line(line: str) -> Optional[InfluxRecord]:
    """Parse one line; returns None for blank/comment lines."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    # measurement[,tags] <space> fields [<space> timestamp]
    sp1 = _find_unescaped(line, " ")
    if sp1 < 0:
        raise InfluxParseError(f"no fields in line: {line!r}")
    head = line[:sp1]
    rest = line[sp1 + 1:]
    sp2 = _find_outside_quotes(rest, " ")
    if sp2 < 0:
        fields_part, ts_part = rest, None
    else:
        fields_part, ts_part = rest[:sp2], rest[sp2 + 1:].strip()

    head_parts = _split_escaped(head, ",")
    measurement = _unescape(head_parts[0])
    if not measurement:
        raise InfluxParseError(f"empty measurement: {line!r}")
    tags: dict[str, str] = {}
    for kv in head_parts[1:]:
        eq = _find_unescaped(kv, "=")  # escaped '=' stays in the key
        if eq <= 0:
            raise InfluxParseError(f"bad tag {kv!r} in line: {line!r}")
        tags[_unescape(kv[:eq])] = _unescape(kv[eq + 1:])

    fields: dict[str, float] = {}
    for kv in _split_outside_quotes(fields_part, ","):
        eq = _find_unescaped(kv, "=")
        if eq <= 0:
            raise InfluxParseError(f"bad field {kv!r} in line: {line!r}")
        name, raw = _unescape(kv[:eq]), kv[eq + 1:]
        if raw.endswith(("i", "u")) and raw[:-1].lstrip("-").isdigit():
            fields[name] = float(raw[:-1])  # integer field
        elif raw.startswith('"') and raw.endswith('"'):
            continue  # string fields don't map to samples
        elif raw in ("t", "T", "true", "True"):
            fields[name] = 1.0
        elif raw in ("f", "F", "false", "False"):
            fields[name] = 0.0
        else:
            try:
                fields[name] = float(raw)
            except ValueError as e:
                raise InfluxParseError(
                    f"bad field value {raw!r} in line: {line!r}") from e
    if not fields:
        raise InfluxParseError(f"no numeric fields in line: {line!r}")

    if ts_part:
        try:
            ts_ms = int(ts_part) // 1_000_000  # Influx default is nanoseconds
        except ValueError as e:
            raise InfluxParseError(
                f"bad timestamp {ts_part!r} in line: {line!r}") from e
    else:
        import time
        ts_ms = int(time.time() * 1000)
    return InfluxRecord(measurement, tags, fields, ts_ms)


def parse_lines(text: str) -> Iterator[InfluxRecord]:
    for line in text.splitlines():
        rec = parse_line(line)
        if rec is not None:
            yield rec


_TRUE = ("t", "T", "true", "True")
_FALSE = ("f", "F", "false", "False")

# shared bound for the gateway's per-series memos (head parse, series
# routing); one module-level constant so tests can shrink it
HEAD_MEMO_MAX = 200_000


def evict_memo_half(memo: dict) -> None:
    """Drop the least-recently-used ~half of a memo dict.

    The old behavior (``memo.clear()`` on overflow) meant one label
    flood wiped every steady series' cached head parse at once — the
    next batch re-parsed the WHOLE fleet's heads in one stampede.
    Every memo HIT re-inserts its entry (``pop`` + set at the call
    sites), so dict order is recency order, not insertion order: a
    flood of one-shot heads sits in the old half and is what gets
    dropped, while the steady fleet — touched every batch — survives.

    Concurrency-tolerant: gateway connection threads share these memos
    without a lock, so the key snapshot is ONE ``list(memo)`` (atomic
    under the GIL — never the incremental iteration that raises
    RuntimeError on a concurrent insert) and deletes use ``pop`` with a
    default (a key another thread already evicted is not an error)."""
    keys = list(memo)
    for k in keys[:len(keys) // 2]:
        memo.pop(k, None)


_HASH_POWS = None


def _hash_pows():
    """Two independent 64-bit positional weight tables for the head
    dedup hash (128 bits total: a silent collision would mislabel
    series, so one 64-bit stream is not enough)."""
    global _HASH_POWS
    if _HASH_POWS is None:
        import numpy as np
        n = 4096                 # max supported head length
        with np.errstate(over="ignore"):
            p1 = np.ones(n, np.uint64)
            p2 = np.ones(n, np.uint64)
            for i in range(1, n):
                p1[i] = p1[i - 1] * np.uint64(0x9E3779B97F4A7C15)
                p2[i] = p2[i - 1] * np.uint64(0xC2B2AE3D27D4EB4F)
        _HASH_POWS = (p1, p2)
    return _HASH_POWS


def parse_batch_columns(text: str, batch_memo: Optional[dict] = None):
    """COLUMNAR batch parse: the whole payload is processed as ONE byte
    array — line/space/equals positions by flatnonzero, the value and
    timestamp tokens extracted with one boolean mask and parsed by
    numpy's C float/int parser, and the repeated ``measurement,tags``
    heads deduplicated by a 128-bit positional reduceat hash so
    per-series work is paid once per batch, not once per line
    (reference throughput anchor: InfluxProtocolParser.scala:65 parses
    bytes in place; jmh GatewayBenchmark.scala:19).

    Serves the common gateway shape: no escapes/quotes/comments, one
    ``name=<float>`` field plus timestamp per line.  Returns ``(heads,
    inverse, fnames, finv, values, ts_ms)`` — unique head strings,
    per-line head index, unique field names, per-line field index,
    float values, int64 epoch-ms stamps — or None when the batch needs
    the general parser (the columnar path is never wrong, only absent).

    ``batch_memo`` (caller-owned dict) short-circuits the head dedup
    when consecutive batches carry the SAME series set in the same
    order — the steady scrape shape — via one byte-compare of the
    concatenated head regions.
    """
    import numpy as np
    if "\\" in text or '"' in text or "#" in text:
        return None
    if not text.endswith("\n"):
        text += "\n"
    data = text.encode("utf-8")
    a = np.frombuffer(data, np.uint8)
    # native fast scan: ONE C pass yields per-line spans + parsed
    # values/timestamps; head dedup + memoization stay up here
    from filodb_tpu import native as _native_mod
    nparse = _native_mod.influx_parser()
    if nparse is not None:
        got = nparse.parse(data)
        if got is nparse.INVALID:
            return None
        starts, sp1, eq1, values, ts_ns = got
        N = len(starts)
        if N == 0:
            return None
        return _resolve_heads(a, data, starts, sp1, eq1, values,
                              ts_ns // 1_000_000, batch_memo)
    nl = np.flatnonzero(a == 10)
    starts = np.empty(len(nl), np.int64)
    starts[0] = 0
    starts[1:] = nl[:-1] + 1
    ends = nl.copy()
    ends -= (a[np.maximum(ends - 1, 0)] == 13)     # \r\n endings
    keep = ends > starts
    starts, ends = starts[keep], ends[keep]
    N = len(starts)
    if N == 0:
        return None
    if (a[starts] == 32).any() or (a[ends - 1] == 32).any():
        return None                                # needs strip: fallback
    L = len(a)
    sp = np.flatnonzero(a == 32)
    i1 = np.searchsorted(sp, starts)
    if i1[-1] >= len(sp):
        return None
    sp1 = sp[np.minimum(i1, len(sp) - 1)]
    if (i1 >= len(sp)).any() or (sp1 >= ends).any():
        return None                                # a line without fields
    i2 = i1 + 1
    sp2 = sp[np.minimum(i2, len(sp) - 1)]
    if not ((i2 < len(sp)) & (sp2 < ends)).all():
        return None                                # missing timestamps
    i3 = i2 + 1
    sp3 = sp[np.minimum(i3, len(sp) - 1)]
    if ((i3 < len(sp)) & (sp3 < ends)).any():
        return None                                # extra spaces
    eqs = np.flatnonzero(a == 61)
    if len(eqs) == 0:
        return None                                # no fields anywhere
    j1 = np.searchsorted(eqs, sp1)
    eq1 = eqs[np.minimum(j1, len(eqs) - 1)]
    if (j1 >= len(eqs)).any() or (eq1 >= sp2).any() \
            or (eq1 == sp1 + 1).any():
        return None                                # field without '='
    j2 = j1 + 1
    eq2 = eqs[np.minimum(j2, len(eqs) - 1)]
    if ((j2 < len(eqs)) & (eq2 < sp2)).any():
        return None                                # '=' in field value
    commas = np.flatnonzero(a == 44)
    if len(commas):
        c1 = np.searchsorted(commas, sp1)
        cc = commas[np.minimum(c1, len(commas) - 1)]
        if ((c1 < len(commas)) & (cc < sp2)).any():
            return None                            # multi-field line

    try:
        # value tokens [eq1+1, sp2]: include the space at sp2 as the
        # separator bytes.split() needs
        idx, _ = range_index(eq1 + 1, sp2 + 1 - (eq1 + 1))
        vt = bytes(a[idx]).split()
        if len(vt) != N:
            return None
        values = np.array(vt, dtype=np.float64)
    except (ValueError, OverflowError):
        return None                    # int/bool/string fields
    # timestamps [sp2+1, ends): pure digits -> vectorized base-10 parse
    # (no per-line bytes objects); signs/garbage fall back to the
    # general parser
    tlen = ends - sp2 - 1
    if (tlen <= 0).any() or int(tlen.max()) > 19:
        return None
    TL = int(tlen.max())
    tidx, toffs = range_index(sp2 + 1, tlen)
    digits = a[tidx].astype(np.int64) - 48
    if ((digits < 0) | (digits > 9)).any():
        return None
    rel = np.arange(len(tidx), dtype=np.int64) - np.repeat(toffs, tlen)
    mat = np.zeros((N, TL), np.int64)
    mat[np.repeat(np.arange(N, dtype=np.int64), tlen),
        rel + np.repeat(TL - tlen, tlen)] = digits  # right-aligned
    if TL <= 10:
        ts_ns = mat @ (10 ** np.arange(TL - 1, -1, -1, dtype=np.int64))
    else:
        lo = mat[:, -10:] @ (10 ** np.arange(9, -1, -1, dtype=np.int64))
        hi = mat[:, :-10] @ (10 ** np.arange(TL - 11, -1, -1,
                                             dtype=np.int64))
        # 19-digit values can exceed int64: combine in uint64 (exact to
        # ~1.8e19) and reject anything past int64 range
        u = hi.astype(np.uint64) * np.uint64(10 ** 10) \
            + lo.astype(np.uint64)
        if (u > np.uint64(2**63 - 1)).any():
            return None
        ts_ns = u.astype(np.int64)
    return _resolve_heads(a, data, starts, sp1, eq1, values,
                          ts_ns // 1_000_000, batch_memo)


def range_index(lo, lens):
    """Flat index array covering per-line [lo_i, lo_i + len_i)."""
    import numpy as np
    offs = np.zeros(len(lens), np.int64)
    np.cumsum(lens[:-1], out=offs[1:] if len(lens) > 1 else offs[:0])
    total = int(lens.sum())
    idx = np.arange(total, dtype=np.int64) + np.repeat(lo - offs, lens)
    return idx, offs


def _resolve_heads(a, data, starts, sp1, eq1, values, ts_ms, batch_memo):
    """Shared tail of the columnar parse: steady-state memo check, field
    names, and the verified head dedup over already-located line spans
    (fed by either the native C scan or the numpy scan).  The byte
    gathers / positional hashes / representative verify each take one C
    pass when the native library is loaded (gather_ranges /
    head_hash128 / verify_heads); the numpy formulations below are the
    bit-identical fallback."""
    import numpy as np
    from filodb_tpu import native as _native_mod
    npr = _native_mod.influx_parser()
    N = len(starts)

    def _gather(lo, hi):
        if npr is not None:
            got = npr.gather(a, lo, hi)
            if got is not None:
                return got
        idx, _ = range_index(lo, hi - lo)
        return a[idx]

    # steady-state memo: ONE byte-compare of the concatenated
    # [head, field-name] regions (everything before each line's '=')
    # short-circuits head dedup AND field-name resolution — the scrape
    # shape re-sends the same series/field layout every interval, only
    # values and timestamps move
    slen = eq1 - starts
    if batch_memo is not None:
        prev = batch_memo.get("line_sig")
        if prev is not None and np.array_equal(prev[1], slen):
            sb8 = _gather(starts, eq1)
            if np.array_equal(sb8, prev[0]):
                heads, inverse, ufn, finv = prev[2:]
                return (heads, inverse, ufn, finv, values, ts_ms)
    # field names: include each line's '=' as the separator
    fn_tokens = bytes(_gather(sp1 + 1, eq1 + 1)).split(b"=")[:-1]
    if len(fn_tokens) != N:
        return None
    ufn_b, finv = np.unique(np.array(fn_tokens), return_inverse=True)
    ufn = [f.decode("utf-8") for f in ufn_b]

    # head dedup: 128-bit positional hash per line; the two 64-bit
    # streams ride a complex128 through np.unique (the float conversion
    # keeps ~52 bits per stream — ample dedup entropy)
    hlen = sp1 - starts
    # reject zero-length heads (a line starting with its separator)
    # BEFORE hashing: np.add.reduceat returns the NEXT segment's element
    # (not 0) for an empty segment, so the numpy fallback would diverge
    # from the C head_hash128 (ADVICE r5 finding 3) — and an empty
    # measurement is malformed anyway (the per-line parser rejects it)
    if not len(hlen) or int(hlen.min()) <= 0:
        return None
    p1, p2 = _hash_pows()
    if int(hlen.max()) >= len(p1):
        return None
    np_head = None          # (hb8, rel) cached for the numpy fallbacks
    got = npr.head_hashes(a, starts, sp1, p1, p2) if npr is not None \
        else None
    if got is not None:
        h1, h2 = got
    else:
        hidx, hoffs = range_index(starts, hlen)
        hb8 = a[hidx]
        rel = np.arange(len(hidx), dtype=np.int64) - np.repeat(hoffs,
                                                               hlen)
        np_head = (hb8, rel)
        hb = hb8.astype(np.uint64)
        with np.errstate(over="ignore"):
            h1 = np.add.reduceat(hb * p1[rel], hoffs)
            h2 = np.add.reduceat(hb * p2[rel], hoffs) \
                ^ hlen.astype(np.uint64)
    hkey = h1.astype(np.float64) + 1j * h2.astype(np.float64)
    _, first_idx, inverse = np.unique(hkey, return_index=True,
                                      return_inverse=True)
    inverse = inverse.ravel()
    # hash-collision guard: the complex128 key keeps ~52 usable bits per
    # stream, so verify every line's head BYTES against its group
    # representative — a collision must fall back to the per-line parser,
    # never silently merge two series (round-4 ADVICE).
    rep = first_idx[inverse]
    okv = npr.verify(a, starts, sp1, rep) if npr is not None else None
    if okv is None:
        maxh = int(hlen.max())
        if np_head is not None:
            hb8, rel = np_head
        else:
            hidx, hoffs = range_index(starts, hlen)
            hb8 = a[hidx]
            rel = np.arange(len(hidx), dtype=np.int64) \
                - np.repeat(hoffs, hlen)
        hm = np.zeros((N, maxh), np.uint8)
        hm[np.repeat(np.arange(N, dtype=np.int64), hlen), rel] = hb8
        okv = not ((hlen != hlen[rep]).any() or (hm != hm[rep]).any())
    if not okv:
        return None
    heads = [data[starts[i]:sp1[i]].decode("utf-8") for i in first_idx]
    if batch_memo is not None:
        batch_memo["line_sig"] = (_gather(starts, eq1), slen.copy(),
                                  heads, inverse, ufn, finv)
    return (heads, inverse, ufn, finv, values, ts_ms)


def parse_head(head: str) -> tuple[str, dict[str, str]]:
    """``measurement,tag=v,...`` (no escapes) -> (measurement, tags)."""
    parts = head.split(",")
    measurement = parts[0]
    if not measurement:
        raise InfluxParseError(f"empty measurement: {head!r}")
    tags: dict[str, str] = {}
    for kv in parts[1:]:
        k, eq, v = kv.partition("=")
        if not k or not eq:
            raise InfluxParseError(f"bad tag {kv!r} in head: {head!r}")
        tags[k] = v
    return measurement, tags


def parse_lines_fast(text: str, head_memo: Optional[dict] = None,
                     _columns_checked: bool = False) -> list[InfluxRecord]:
    """Batch parser for the gateway ingest hot path (reference:
    InfluxProtocolParser.scala:65 parses bytes in place per line; the
    python analog gets its speed from C-level ``str`` splits plus HEAD
    MEMOIZATION — in scrape traffic the ``measurement,tags`` prefix of a
    series repeats every interval, so its tag-dict is built once, not
    per line).  Lines containing escapes, quotes, or comments take the
    per-character :func:`parse_line` path — the fast path is never
    wrong, only absent.

    ``head_memo`` lets a long-lived caller (the gateway server) carry
    the prefix cache across batches."""
    memo: dict = {} if head_memo is None else head_memo
    # _columns_checked: the caller already ran parse_batch_columns on
    # this payload and got None — skip the redundant O(payload) scan
    cols = None if _columns_checked else parse_batch_columns(text)
    if cols is not None:
        uheads, inv, ufn, finv, values, ts_ms = cols
        parsed = []
        for h in uheads:
            # pop + re-insert on hit: keeps dict order = recency order,
            # so overflow eviction drops flood garbage, not the fleet
            got = memo.pop(h, None)
            if got is None:
                if len(memo) >= HEAD_MEMO_MAX:
                    evict_memo_half(memo)
                got = parse_head(h)
            memo[h] = got
            parsed.append(got)
        return [InfluxRecord(parsed[hi][0], dict(parsed[hi][1]),
                             {ufn[fi]: float(v)}, int(t))
                for hi, fi, v, t in zip(inv, finv, values, ts_ms)]
    recs: list[InfluxRecord] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or "\\" in line or '"' in line or line[0] == "#":
            rec = parse_line(line)
            if rec is not None:
                recs.append(rec)
            continue
        sp = line.find(" ")
        if sp < 0:
            raise InfluxParseError(f"no fields in line: {line!r}")
        head = line[:sp]
        got = memo.pop(head, None)  # pop+set on hit: recency order
        if got is None:
            if len(memo) >= HEAD_MEMO_MAX:  # bound churn from label floods
                evict_memo_half(memo)
            got = parse_head(head)
        memo[head] = got
        measurement, tags = got
        rest = line[sp + 1:]
        sp2 = rest.find(" ")
        if sp2 < 0:
            fields_part, ts_part = rest, None
        else:
            fields_part, ts_part = rest[:sp2], rest[sp2 + 1:]
        fields: dict[str, float] = {}
        for kv in fields_part.split(","):
            name, eq, raw = kv.partition("=")
            if not name or not eq:
                raise InfluxParseError(
                    f"bad field {kv!r} in line: {line!r}")
            if raw.endswith(("i", "u")) and raw[:-1].lstrip("-").isdigit():
                fields[name] = float(raw[:-1])
            elif raw in _TRUE:
                fields[name] = 1.0
            elif raw in _FALSE:
                fields[name] = 0.0
            else:
                try:
                    fields[name] = float(raw)
                except ValueError as e:
                    raise InfluxParseError(
                        f"bad field value {raw!r} in line: {line!r}") from e
        if not fields:
            raise InfluxParseError(f"no numeric fields in line: {line!r}")
        if ts_part:
            try:
                ts_ms = int(ts_part) // 1_000_000
            except ValueError as e:
                raise InfluxParseError(
                    f"bad timestamp {ts_part!r} in line: {line!r}") from e
        else:
            import time
            ts_ms = int(time.time() * 1000)
        # copy the memoized tag dict: records are mutable and outlive
        # the batch; the memo must stay pristine
        recs.append(InfluxRecord(measurement, dict(tags), fields, ts_ms))
    return recs


def prom_metric_name(measurement: str, fname: str) -> str:
    """Influx field -> Prometheus metric naming (reference:
    InfluxPromSingleRecord: measurement_field, plain measurement for
    the 'value' field).  Shared by the per-record and columnar ingest
    paths so the rule cannot drift between them."""
    return measurement if fname == "value" else f"{measurement}_{fname}"


def to_prom_samples(rec: InfluxRecord,
                    default_tags: Optional[Mapping[str, str]] = None
                    ) -> Iterator[tuple[str, dict, float]]:
    """InfluxRecord -> (metric_name, tags, value) gauge samples."""
    base = dict(default_tags or {})
    base.update(rec.tags)
    for fname, fval in rec.fields.items():
        yield prom_metric_name(rec.measurement, fname), base, fval
