"""Influx line protocol parser -> input records.

Capability match for the reference's gateway conversion layer (reference:
gateway/src/main/scala/filodb/gateway/conversion/
InfluxProtocolParser.scala:65, InfluxRecord.scala — parse
``measurement,tag=v,... field=1.0,... <ts>`` lines; single-field records
map to the gauge/counter prom schemas, ``sum``/``count``/bucket fields
map to histograms; InputRecord.scala:15 defines the conversion target).

Escapes per the Influx spec: ``\\,`` ``\\ `` ``\\=`` in identifiers/tags,
``\\"`` in string field values.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Mapping, Optional


class InfluxParseError(ValueError):
    pass


@dataclasses.dataclass
class InfluxRecord:
    """One parsed line (reference: InfluxPromSingleRecord /
    InfluxHistogramRecord)."""

    measurement: str
    tags: dict[str, str]
    fields: dict[str, float]
    timestamp_ms: int

    def kind(self) -> str:
        """gauge | histogram — histogram when bucket-style fields present
        (reference: InfluxProtocolParser.record: histogram chosen when
        fields are sum/count/+Inf/le buckets)."""
        names = set(self.fields)
        if "sum" in names and "count" in names and len(names) > 2:
            return "histogram"
        return "gauge"


def _split_escaped(text: str, sep: str) -> list[str]:
    """Split on unescaped sep, PRESERVING escape sequences in the pieces
    (so later splits on '=' still see which ones were escaped)."""
    out, cur, i = [], [], 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            cur.append(text[i:i + 2])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unescape(text: str) -> str:
    out, i = [], 0
    while i < len(text):
        if text[i] == "\\" and i + 1 < len(text):
            out.append(text[i + 1])
            i += 2
        else:
            out.append(text[i])
            i += 1
    return "".join(out)


def _find_unescaped(text: str, ch: str, start: int = 0) -> int:
    i = start
    while i < len(text):
        if text[i] == "\\":
            i += 2
            continue
        if text[i] == ch:
            return i
        i += 1
    return -1


def _find_outside_quotes(text: str, ch: str) -> int:
    """First unescaped ``ch`` that is not inside a double-quoted string
    (field values may contain spaces/commas in quotes)."""
    i = 0
    in_quotes = False
    while i < len(text):
        c = text[i]
        if c == "\\":
            i += 2
            continue
        if c == '"':
            in_quotes = not in_quotes
        elif c == ch and not in_quotes:
            return i
        i += 1
    return -1


def _split_outside_quotes(text: str, sep: str) -> list[str]:
    out, cur, i = [], [], 0
    in_quotes = False
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            cur.append(text[i:i + 2])
            i += 2
            continue
        if c == '"':
            in_quotes = not in_quotes
            cur.append(c)
        elif c == sep and not in_quotes:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def parse_line(line: str) -> Optional[InfluxRecord]:
    """Parse one line; returns None for blank/comment lines."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    # measurement[,tags] <space> fields [<space> timestamp]
    sp1 = _find_unescaped(line, " ")
    if sp1 < 0:
        raise InfluxParseError(f"no fields in line: {line!r}")
    head = line[:sp1]
    rest = line[sp1 + 1:]
    sp2 = _find_outside_quotes(rest, " ")
    if sp2 < 0:
        fields_part, ts_part = rest, None
    else:
        fields_part, ts_part = rest[:sp2], rest[sp2 + 1:].strip()

    head_parts = _split_escaped(head, ",")
    measurement = _unescape(head_parts[0])
    if not measurement:
        raise InfluxParseError(f"empty measurement: {line!r}")
    tags: dict[str, str] = {}
    for kv in head_parts[1:]:
        eq = _find_unescaped(kv, "=")  # escaped '=' stays in the key
        if eq <= 0:
            raise InfluxParseError(f"bad tag {kv!r} in line: {line!r}")
        tags[_unescape(kv[:eq])] = _unescape(kv[eq + 1:])

    fields: dict[str, float] = {}
    for kv in _split_outside_quotes(fields_part, ","):
        eq = _find_unescaped(kv, "=")
        if eq <= 0:
            raise InfluxParseError(f"bad field {kv!r} in line: {line!r}")
        name, raw = _unescape(kv[:eq]), kv[eq + 1:]
        if raw.endswith(("i", "u")) and raw[:-1].lstrip("-").isdigit():
            fields[name] = float(raw[:-1])  # integer field
        elif raw.startswith('"') and raw.endswith('"'):
            continue  # string fields don't map to samples
        elif raw in ("t", "T", "true", "True"):
            fields[name] = 1.0
        elif raw in ("f", "F", "false", "False"):
            fields[name] = 0.0
        else:
            try:
                fields[name] = float(raw)
            except ValueError as e:
                raise InfluxParseError(
                    f"bad field value {raw!r} in line: {line!r}") from e
    if not fields:
        raise InfluxParseError(f"no numeric fields in line: {line!r}")

    if ts_part:
        try:
            ts_ms = int(ts_part) // 1_000_000  # Influx default is nanoseconds
        except ValueError as e:
            raise InfluxParseError(
                f"bad timestamp {ts_part!r} in line: {line!r}") from e
    else:
        import time
        ts_ms = int(time.time() * 1000)
    return InfluxRecord(measurement, tags, fields, ts_ms)


def parse_lines(text: str) -> Iterator[InfluxRecord]:
    for line in text.splitlines():
        rec = parse_line(line)
        if rec is not None:
            yield rec


def to_prom_samples(rec: InfluxRecord,
                    default_tags: Optional[Mapping[str, str]] = None
                    ) -> Iterator[tuple[str, dict, float]]:
    """InfluxRecord -> (metric_name, tags, value) gauge samples
    (reference: InfluxPromSingleRecord naming: measurement_field, plain
    measurement for the 'value' field)."""
    base = dict(default_tags or {})
    base.update(rec.tags)
    for fname, fval in rec.fields.items():
        metric = rec.measurement if fname == "value" \
            else f"{rec.measurement}_{fname}"
        yield metric, base, fval
