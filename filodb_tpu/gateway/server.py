"""Gateway: TCP Influx line-protocol edge -> sharded record containers.

Capability match for the reference's GatewayServer (reference:
gateway/src/main/scala/filodb/gateway/GatewayServer.scala:58 — Netty TCP
server accepting Influx line protocol, converting to RecordBuilder
containers, computing the target shard with ShardMapper + spread, and
publishing per-shard to Kafka).  The stdlib socketserver replaces Netty;
the QueueStreamFactory (or any per-shard publish function) replaces the
Kafka producer.
"""

from __future__ import annotations

import socketserver
import threading
from typing import Callable, Mapping, Optional

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DatasetOptions, Schema
from filodb_tpu.gateway.influx import InfluxParseError, parse_line
from filodb_tpu.parallel.shardmap import ShardMapper


class ShardingPublisher:
    """Routes samples to shards exactly like the reference gateway:
    RecordBuilder per shard, shard = ShardMapper bit-splice of
    (shardKeyHash, partHash, spread)."""

    def __init__(self, schema: Schema, mapper: ShardMapper,
                 publish: Callable[[int, bytes], None], spread: int = 1,
                 options: Optional[DatasetOptions] = None,
                 container_size: int = 64 * 1024):
        self.schema = schema
        self.mapper = mapper
        self.publish = publish  # (shard, container) -> ()
        self.spread = spread
        self.options = options or DatasetOptions()
        self.container_size = container_size
        self._builders: dict[int, RecordBuilder] = {}
        self._lock = threading.Lock()
        self.samples_in = 0
        self.parse_errors = 0

    def _shard_of(self, tags: Mapping[str, str]) -> int:
        from filodb_tpu.core.record import partition_hash, shard_key_hash
        shash = shard_key_hash(tags, self.options)
        phash = partition_hash(tags, self.options)
        return self.mapper.ingestion_shard(shash, phash, self.spread) \
            % self.mapper.num_shards

    def add_sample(self, metric: str, tags: Mapping[str, str],
                   timestamp_ms: int, value: float) -> int:
        """Returns the shard the sample routed to."""
        # normalize once: the builder skips its own __name__ rewrite when
        # the metric column is already present
        norm = dict(tags)
        norm[self.options.metric_column] = metric
        with self._lock:
            shard = self._shard_of(norm)
            builder = self._builders.get(shard)
            if builder is None:
                builder = self._builders[shard] = RecordBuilder(
                    self.schema, self.options, self.container_size)
            builder.add(timestamp_ms, [value], norm)
            self.samples_in += 1
        return shard

    def ingest_influx_line(self, line: str) -> int:
        """Parse one line and route its samples.  Returns samples added."""
        from filodb_tpu.gateway.influx import to_prom_samples
        try:
            rec = parse_line(line)
        except InfluxParseError:
            self.parse_errors += 1
            return 0
        if rec is None:
            return 0
        n = 0
        for metric, tags, value in to_prom_samples(rec):
            self.add_sample(metric, tags, rec.timestamp_ms, value)
            n += 1
        return n

    def ingest_influx_batch(self, text: str) -> int:
        """Batch ingest: the COLUMNAR path groups the payload's lines by
        (series head, field), resolves shard + normalized tags once per
        series from a cross-batch memo, and lands each group through ONE
        vectorized RecordBuilder.add_series — per-line Python work
        drops to near zero on scrape-shaped traffic (reference:
        GatewayServer's per-series InputRecords + RecordBuilder reuse).
        Falls back to per-record, then per-line ingestion; malformed
        lines count as parse_errors, matching ingest_influx_line."""
        from filodb_tpu.gateway.influx import (parse_batch_columns,
                                               parse_lines_fast,
                                               to_prom_samples)
        if not hasattr(self, "_batch_memo"):
            self._batch_memo = {}
        cols = parse_batch_columns(text, self._batch_memo)
        if cols is not None:
            return self._ingest_columns(cols)
        if not hasattr(self, "_head_memo"):
            self._head_memo = {}
        try:
            recs = parse_lines_fast(text, self._head_memo,
                                    _columns_checked=True)
        except InfluxParseError:
            # a bad line poisons the whole fast batch: fall back to
            # per-line ingestion so good lines still land
            return sum(self.ingest_influx_line(ln)
                       for ln in text.splitlines())
        n = 0
        for rec in recs:
            for metric, tags, value in to_prom_samples(rec):
                self.add_sample(metric, tags, rec.timestamp_ms, value)
                n += 1
        return n

    def _ingest_columns(self, cols) -> int:
        import numpy as np

        from filodb_tpu.gateway.influx import parse_head, prom_metric_name
        uheads, inv, ufn, finv, values, ts_ms = cols
        if not hasattr(self, "_series_memo"):
            self._series_memo = {}
        combo = inv.astype(np.int64) * len(ufn) + finv
        order = np.argsort(combo, kind="stable")
        sc = combo[order]
        starts = np.flatnonzero(
            np.concatenate([[True], sc[1:] != sc[:-1]]))
        ends = np.append(starts[1:], len(order))
        # resolve EVERY group's series memo first: a malformed head
        # mid-batch must skip only its own lines (counted as parse
        # errors), never abort after some groups already landed
        groups = []
        bad = 0
        for s, e in zip(starts, ends):
            rows = order[s:e]
            head = uheads[int(inv[rows[0]])]
            fname = ufn[int(finv[rows[0]])]
            key = (head, fname)
            got = self._series_memo.get(key)
            if got is None:
                try:
                    measurement, tags = parse_head(head)
                except InfluxParseError:
                    bad += len(rows)
                    continue
                if len(self._series_memo) > 200_000:
                    self._series_memo.clear()
                metric = prom_metric_name(measurement, fname)
                norm = dict(tags)
                norm[self.options.metric_column] = metric
                from filodb_tpu.core.record import (canonical_partkey,
                                                    partition_hash,
                                                    shard_key_hash)
                # memoize shard AND the per-series hashes/partkey: the
                # record build then skips recomputing them every batch
                shash = shard_key_hash(norm, self.options)
                phash = partition_hash(norm, self.options)
                shard = self.mapper.ingestion_shard(
                    shash, phash, self.spread) % self.mapper.num_shards
                got = self._series_memo[key] = (
                    shard, shash, phash, canonical_partkey(norm))
            groups.append((got, rows))
        self.parse_errors += bad
        n = 0
        with self._lock:
            for (shard, shash, phash, pk), rows in groups:
                builder = self._builders.get(shard)
                if builder is None:
                    builder = self._builders[shard] = RecordBuilder(
                        self.schema, self.options, self.container_size)
                builder.add_series_hashed(ts_ms[rows], [values[rows]],
                                          shash, phash, pk)
                n += len(rows)
            self.samples_in += n
        return n

    def flush(self) -> int:
        """Publish all pending containers; returns containers published.
        Drains builders under the lock — RecordBuilder is not thread-safe
        and concurrent add_sample/flush would otherwise lose containers."""
        with self._lock:
            drained = [(shard, c) for shard, b in self._builders.items()
                       for c in b.containers()]
        n = 0
        for shard, c in drained:
            self.publish(shard, c)
            n += 1
        return n


class GatewayServer:
    """TCP server speaking Influx line protocol, one line per record
    (reference: GatewayServer Netty pipeline)."""

    def __init__(self, publisher: ShardingPublisher, host: str = "127.0.0.1",
                 port: int = 0, flush_every: int = 128):
        self.publisher = publisher
        self.host = host
        self.port = port
        self.flush_every = flush_every
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        gw = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                # batch lines so the COLUMNAR ingest path serves the
                # wire traffic too (per-line ingest pays per-line parse
                # + lock overhead — the cost the columnar path removes)
                buf: list[str] = []
                for raw in self.rfile:
                    buf.append(raw.decode("utf-8", "replace"))
                    if len(buf) >= gw.flush_every:
                        gw.publisher.ingest_influx_batch("".join(buf))
                        buf.clear()
                        gw.publisher.flush()
                if buf:
                    gw.publisher.ingest_influx_batch("".join(buf))
                gw.publisher.flush()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # scoped here, not on the stdlib class

        self._server = _Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="gateway", daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
