"""Gateway: TCP Influx line-protocol edge -> sharded record containers.

Capability match for the reference's GatewayServer (reference:
gateway/src/main/scala/filodb/gateway/GatewayServer.scala:58 — Netty TCP
server accepting Influx line protocol, converting to RecordBuilder
containers, computing the target shard with ShardMapper + spread, and
publishing per-shard to Kafka).  The stdlib socketserver replaces Netty;
the QueueStreamFactory (or any per-shard publish function) replaces the
Kafka producer.
"""

from __future__ import annotations

import queue
import socketserver
import threading
import time
from typing import Callable, Mapping, Optional

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DatasetOptions, Schema
from filodb_tpu.gateway.influx import InfluxParseError, parse_line
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.utils.observability import TRACER, ingest_metrics

_METRICS = ingest_metrics()


class ShardingPublisher:
    """Routes samples to shards exactly like the reference gateway:
    RecordBuilder per shard, shard = ShardMapper bit-splice of
    (shardKeyHash, partHash, spread)."""

    def __init__(self, schema: Schema, mapper: ShardMapper,
                 publish: Callable[[int, bytes], None], spread: int = 1,
                 options: Optional[DatasetOptions] = None,
                 container_size: int = 64 * 1024,
                 quota: Optional[object] = None):
        self.schema = schema
        self.mapper = mapper
        self.publish = publish  # (shard, container) -> ()
        self.spread = spread
        self.options = options or DatasetOptions()
        self.container_size = container_size
        # cardinality-quota edge shed (workload/quota.py SeriesQuota):
        # a series-memo MISS for an over-quota tenant drops that series'
        # samples HERE, before any container build — advisory only, the
        # shard-side check at part-id assignment stays authoritative
        self.quota = quota
        self._builders: dict[int, RecordBuilder] = {}
        self._lock = threading.Lock()
        self.samples_in = 0
        self.parse_errors = 0
        # elastic resharding (ISSUE 13 satellite): the series memo and
        # the replayable group plan BAKE shard assignments in — after a
        # live split commits, replaying them would keep publishing
        # migrated series to the retired parent forever.  Every batch
        # entry validates this against ShardMapper.topology_generation
        # (one int compare) and rehashes on a bump.
        self._memo_generation = mapper.topology_generation

    def _check_topology_generation(self) -> None:
        gen = self.mapper.topology_generation
        if gen != self._memo_generation:
            self._memo_generation = gen
            if hasattr(self, "_series_memo"):
                self._series_memo.clear()
            self._group_plan = None

    def _shard_of(self, tags: Mapping[str, str]) -> int:
        from filodb_tpu.core.record import partition_hash, shard_key_hash
        shash = shard_key_hash(tags, self.options)
        phash = partition_hash(tags, self.options)
        return self.mapper.ingestion_shard(shash, phash, self.spread) \
            % self.mapper.num_shards

    def add_sample(self, metric: str, tags: Mapping[str, str],
                   timestamp_ms: int, value: float) -> int:
        """Returns the shard the sample routed to."""
        # normalize once: the builder skips its own __name__ rewrite when
        # the metric column is already present
        norm = dict(tags)
        norm[self.options.metric_column] = metric
        with self._lock:
            shard = self._shard_of(norm)
            builder = self._builders.get(shard)
            if builder is None:
                builder = self._builders[shard] = RecordBuilder(  # filolint: disable=bounded-cache — keyed by shard number, bounded by num_shards
                    self.schema, self.options, self.container_size)
            builder.add(timestamp_ms, [value], norm)
            self.samples_in += 1
        return shard

    def ingest_influx_line(self, line: str) -> int:
        """Parse one line and route its samples.  Returns samples added."""
        err0 = self.parse_errors
        n = self._ingest_line(line)
        if self.parse_errors > err0:
            _METRICS["parse_errors"].inc(self.parse_errors - err0)
        if n:
            _METRICS["samples"].inc(n)
        return n

    def _ingest_line(self, line: str) -> int:
        """Uncounted per-line path (the batch wrapper counts by delta)."""
        from filodb_tpu.gateway.influx import to_prom_samples
        try:
            rec = parse_line(line)
        except InfluxParseError:
            self.parse_errors += 1
            return 0
        if rec is None:
            return 0
        n = 0
        for metric, tags, value in to_prom_samples(rec):
            self.add_sample(metric, tags, rec.timestamp_ms, value)
            n += 1
        return n

    def ingest_influx_batch(self, text: str) -> int:
        """Instrumented batch-ingest entry (ISSUE 2): one span + the
        filodb_ingest_* family per wire batch; parse errors anywhere in
        the fallback chain count by delta."""
        t0 = time.perf_counter()
        err0 = self.parse_errors
        try:
            with TRACER.span("gateway.ingest_batch"):
                n = self._ingest_batch(text)
        finally:
            _METRICS["batch_seconds"].observe(time.perf_counter() - t0)
            errs = self.parse_errors - err0
            if errs > 0:
                _METRICS["parse_errors"].inc(errs)
        if n:
            _METRICS["samples"].inc(n)
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("ingest.batch", samples=n, parse_errors=errs,
                      seconds=round(time.perf_counter() - t0, 6))
        return n

    def _ingest_batch(self, text: str) -> int:
        """Batch ingest: the COLUMNAR path groups the payload's lines by
        (series head, field), resolves shard + normalized tags once per
        series from a cross-batch memo, and lands each group through ONE
        vectorized RecordBuilder.add_series — per-line Python work
        drops to near zero on scrape-shaped traffic (reference:
        GatewayServer's per-series InputRecords + RecordBuilder reuse).
        Falls back to per-record, then per-line ingestion; malformed
        lines count as parse_errors, matching ingest_influx_line."""
        from filodb_tpu.gateway.influx import (parse_batch_columns,
                                               parse_lines_fast,
                                               to_prom_samples)
        # a topology-generation bump (live shard split) invalidates the
        # shard-carrying memos below before any line resolves
        self._check_topology_generation()
        if not hasattr(self, "_batch_memo"):
            self._batch_memo = {}
        cols = parse_batch_columns(text, self._batch_memo)
        if cols is not None:
            return self._ingest_columns(cols)
        if not hasattr(self, "_head_memo"):
            self._head_memo = {}
        try:
            recs = parse_lines_fast(text, self._head_memo,
                                    _columns_checked=True)
        except InfluxParseError:
            # a bad line poisons the whole fast batch: fall back to
            # per-line ingestion so good lines still land
            return sum(self._ingest_line(ln)
                       for ln in text.splitlines())
        n = 0
        for rec in recs:
            for metric, tags, value in to_prom_samples(rec):
                self.add_sample(metric, tags, rec.timestamp_ms, value)
                n += 1
        return n

    def _ingest_columns(self, cols) -> int:
        import numpy as np

        from filodb_tpu.core.record import record_dtype
        from filodb_tpu.core.schemas import ColumnType
        from filodb_tpu.gateway import influx as influx_mod
        from filodb_tpu.gateway.influx import parse_head, prom_metric_name
        uheads, inv, ufn, finv, values, ts_ms = cols
        # steady-state: the parser's memo returns the SAME inv/finv
        # objects while the series/field layout is byte-identical, so the
        # whole group resolution + record layout is replayable as a plan
        plan = getattr(self, "_group_plan", None)
        if plan is not None and plan["key"] == (id(inv), id(finv),
                                                len(inv)):
            return self._ingest_planned(plan, values, ts_ms)
        if not hasattr(self, "_series_memo"):
            self._series_memo = {}
        combo = inv.astype(np.int64) * len(ufn) + finv
        order = np.argsort(combo, kind="stable")
        sc = combo[order]
        gstarts = np.flatnonzero(
            np.concatenate([[True], sc[1:] != sc[:-1]]))
        gends = np.append(gstarts[1:], len(order))
        ngroups = len(gstarts)
        # resolve EVERY group's series memo first: a malformed head
        # mid-batch must skip only its own lines (counted as parse
        # errors), never abort after some groups already landed
        shard_g = np.empty(ngroups, np.int64)
        shash_g = np.empty(ngroups, np.uint32)
        phash_g = np.empty(ngroups, np.uint32)
        pk_g: list = [b""] * ngroups
        good = np.ones(ngroups, bool)
        bad = 0
        qdrop = 0
        for gi in range(ngroups):
            r0 = int(order[gstarts[gi]])
            key = (uheads[int(inv[r0])], ufn[int(finv[r0])])
            # pop + re-insert below keeps memo order = recency order
            got = self._series_memo.pop(key, None)
            if got is None:
                try:
                    measurement, tags = parse_head(key[0])
                except InfluxParseError:
                    good[gi] = False
                    bad += int(gends[gi] - gstarts[gi])
                    continue
                if len(self._series_memo) >= influx_mod.HEAD_MEMO_MAX:
                    # evict the LRU half, never the whole memo: a label
                    # flood must not force a full re-resolve stampede
                    # of the steady fleet (ISSUE 6 satellite)
                    influx_mod.evict_memo_half(self._series_memo)
                metric = prom_metric_name(measurement, key[1])
                norm = dict(tags)
                norm[self.options.metric_column] = metric
                if self.quota is not None and self.quota.over_limit(norm):
                    # memo miss ~= possibly-new series: an over-quota
                    # tenant's samples shed at the edge, NOT memoized —
                    # the tenant may free quota and come back under
                    good[gi] = False
                    n_rows = int(gends[gi] - gstarts[gi])
                    qdrop += n_rows
                    self.quota.note_dropped_samples(norm, n_rows)
                    continue
                from filodb_tpu.core.record import (canonical_partkey,
                                                    partition_hash,
                                                    shard_key_hash)
                # memoize shard AND the per-series hashes/partkey: the
                # batch record build gathers them, never recomputes
                shash = shard_key_hash(norm, self.options)
                phash = partition_hash(norm, self.options)
                shard = self.mapper.ingestion_shard(
                    shash, phash, self.spread) % self.mapper.num_shards
                got = (shard, shash, phash, canonical_partkey(norm))
            self._series_memo[key] = got
            shard_g[gi], shash_g[gi], phash_g[gi], pk_g[gi] = got
        data_cols = self.schema.data.columns[1:]
        if len(data_cols) != 1 or data_cols[0].ctype != ColumnType.DOUBLE:
            # general schemas take the per-series path
            self.parse_errors += bad
            return self._ingest_groups_per_series(
                order, gstarts, gends, good, shard_g, shash_g, phash_g,
                pk_g, values, ts_ms)
        # -- ONE structured-array build for the whole batch, sliced per
        # shard: per-row fields GATHER from the per-series arrays (the
        # per-series RecordBuilder call was the e2e bottleneck at 1e6
        # samples/s; reference: GatewayServer's container reuse,
        # GatewayServer.scala:58).  Everything except the per-batch
        # timestamp/value patch is captured in a PLAN, memoized on the
        # parser's memo-identity (see _ingest_planned).
        counts = gends - gstarts
        srow = np.repeat(np.arange(ngroups), counts)   # series per pos
        keep = good[srow]
        rows = order[keep]
        sidx = srow[keep]
        pklen_g = np.fromiter((len(p) for p in pk_g), np.int64, ngroups)
        row_pl = pklen_g[sidx]
        pls = []
        for pl in np.unique(row_pl):
            sel = row_pl == pl
            rsel, ssel = rows[sel], sidx[sel]
            # shard-major so each shard's records slice contiguously
            bysh = np.argsort(shard_g[ssel], kind="stable")
            rsel, ssel = rsel[bysh], ssel[bysh]
            dt = record_dtype(self.schema, int(pl))
            proto = np.zeros(len(rsel), dt)
            proto["schema"] = self.schema.schema_hash
            proto["shash"] = shash_g[ssel]
            proto["phash"] = phash_g[ssel]
            proto["pklen"] = pl
            if pl:
                uniq_s, pinv = np.unique(ssel, return_inverse=True)
                pkm = np.frombuffer(
                    b"".join(pk_g[int(u)] for u in uniq_s),
                    np.uint8).reshape(len(uniq_s), int(pl))
                proto["pk"] = pkm.view(f"V{int(pl)}")[:, 0][pinv]
            sh = shard_g[ssel]
            seg = np.flatnonzero(np.concatenate(
                [[True], sh[1:] != sh[:-1]]))
            seg_end = np.append(seg[1:], len(sh))
            segs = [(int(sh[a0]), int(a0), int(b0))
                    for a0, b0 in zip(seg, seg_end)]
            pls.append({"proto": proto, "rsel": rsel, "segs": segs})
        plan = {"key": (id(inv), id(finv), len(inv)),
                "refs": (inv, finv), "pls": pls, "bad": bad}
        if not qdrop:
            # quota-shed groups must NOT bake into a replayable plan:
            # the tenant can drop back under quota, and replay would
            # keep silently excluding (and would mis-count the drop)
            self._group_plan = plan
        return self._ingest_planned(plan, values, ts_ms)

    def _ingest_planned(self, plan, values, ts_ms) -> int:
        """Execute a cached batch-build plan: copy each pre-filled record
        prototype (hashes, partkeys, shard layout baked in), patch
        timestamps + values, and append contiguous per-shard slices —
        the steady-state scrape path costs ~8 numpy ops per batch."""
        self.parse_errors += plan["bad"]
        n = 0
        with self._lock:
            for p in plan["pls"]:
                rec = p["proto"].copy()
                rec["ts"] = ts_ms[p["rsel"]]
                rec["c0"] = values[p["rsel"]]
                blob = rec.tobytes()
                isz = rec.dtype.itemsize
                for shard, a0, b0 in p["segs"]:
                    builder = self._builders.get(shard)
                    if builder is None:
                        builder = self._builders[shard] = RecordBuilder(
                            self.schema, self.options,
                            self.container_size)
                    builder.append_encoded(blob[a0 * isz:b0 * isz],
                                            isz, b0 - a0)
                n += len(p["rsel"])
            self.samples_in += n
        return n

    def _ingest_groups_per_series(self, order, gstarts, gends, good,
                                  shard_g, shash_g, phash_g, pk_g,
                                  values, ts_ms) -> int:
        n = 0
        with self._lock:
            for gi in range(len(gstarts)):
                if not good[gi]:
                    continue
                rows = order[gstarts[gi]:gends[gi]]
                shard = int(shard_g[gi])
                builder = self._builders.get(shard)
                if builder is None:
                    builder = self._builders[shard] = RecordBuilder(
                        self.schema, self.options, self.container_size)
                builder.add_series_hashed(
                    ts_ms[rows], [values[rows]], int(shash_g[gi]),
                    int(phash_g[gi]), pk_g[gi])
                n += len(rows)
            self.samples_in += n
        return n

    def flush(self) -> int:
        """Publish all pending containers; returns containers published.
        Drains builders under the lock — RecordBuilder is not thread-safe
        and concurrent add_sample/flush would otherwise lose containers."""
        with self._lock:
            drained = [(shard, c) for shard, b in self._builders.items()
                       for c in b.containers()]
        n = 0
        for shard, c in drained:
            self.publish(shard, c)
            n += 1
        return n


class _FailureEpisodes:
    """The one failure-telemetry shape for every dual-write delivery
    path (sync local, lane worker, lane overflow, missing transport).
    Counter inc per container (total loss must be measurable); flight
    event once per node EPISODE, re-armed by the next successful
    delivery — a wedged peer under heavy ingest (thousands of
    containers/s) must not evict every other diagnostic from the
    bounded flight ring during exactly the incident the recorder
    exists for.  Owned per :class:`ReplicaFanout`, NOT module-global:
    in-process multi-node clusters run one fanout per server for the
    same dataset, and shared state would let server A's episode
    suppress server B's first event (the per-server-state lesson of
    PR 11's WatermarkLedger)."""

    def __init__(self, dataset: str):
        self.dataset = dataset
        self._failing: set = set()
        self._lock = threading.Lock()

    def fail(self, node: str, shard: int, error: str) -> None:
        _METRICS["replica_publish_failures"].inc(dataset=self.dataset,
                                                 node=node)
        with self._lock:
            first = node not in self._failing
            if first:
                self._failing.add(node)
        if first:
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("ingest.replica_publish_failed",
                          dataset=self.dataset, shard=shard, node=node,
                          error=error[:200])

    def ok(self, node: str) -> None:
        """A successful delivery ends the node's failure episode — the
        NEXT failure flight-records again."""
        with self._lock:
            self._failing.discard(node)


_LANE_STOP = object()


class _ReplicaLane:
    """One PEER's asynchronous delivery lane: a bounded queue drained
    by a daemon worker.  A wedged peer fills its own lane and starts
    dropping (counted, flight-recorded) — it can never stall the
    gateway publish path or the other replicas' deliveries."""

    def __init__(self, dataset: str, node: str,
                 push: Callable[[int, bytes], None], max_queued: int,
                 episodes: _FailureEpisodes):
        self.dataset = dataset
        self.node = node
        self.push = push
        self.episodes = episodes
        self._stopped = False
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queued)
        self._thread = threading.Thread(
            target=self._run, name=f"replica-push-{dataset}-{node}",
            daemon=True)
        self._thread.start()

    def enqueue(self, shard: int, container: bytes) -> bool:
        try:
            self._q.put_nowait((shard, container))
            return True
        except queue.Full:
            self.episodes.fail(self.node, shard,
                               "delivery queue full (peer wedged or "
                               "unreachable)")
            return False

    def _run(self) -> None:
        while not self._stopped:
            try:
                item = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is _LANE_STOP or self._stopped:
                self._q.task_done()
                break
            shard, container = item
            try:
                self.push(shard, container)
                _METRICS["replica_publishes"].inc(dataset=self.dataset,
                                                  node=self.node)
                self.episodes.ok(self.node)
            except Exception as e:  # noqa: BLE001 — this replica lags
                self.episodes.fail(self.node, shard, str(e))
            finally:
                self._q.task_done()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Best-effort wait for the lane to empty (tests/shutdown)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        """Stop the worker NOW; still-queued containers are dropped.
        A node being shut down must not keep delivering to peers from
        beyond the grave — callers that want a flush first call
        :meth:`drain` before closing."""
        self._stopped = True
        try:
            self._q.put_nowait(_LANE_STOP)
        except queue.Full:
            pass  # worker notices _stopped within its 250 ms poll
        self._thread.join(timeout=2.0)


class ReplicaFanout:
    """Dual-write publish hook (ISSUE 7): delivers each container to
    EVERY replica of its shard.

    Plugs in as a ShardingPublisher ``publish`` callable.  The replica
    set comes from the mapper at publish time (a membership change
    reroutes the very next container), and each replica node maps to
    its own transport — the local in-proc queue for this node
    (synchronous: local ingest stays in lockstep with the gateway), an
    HTTP container push (``/ingest/<ds>/<shard>``) for peers, delivered
    through per-peer ASYNC lanes (:class:`_ReplicaLane`) so one
    slow/wedged peer can neither stall the gateway nor the other
    replicas.  A failed or overflowed per-replica delivery is counted
    and flight-recorded; the lagging replica is visibly behind in its
    recovery watermarks (PR 11 ledger chain).  Queue-transport
    replication is best-effort per replica — the broker transport is
    the durable replicated log.

    Broker-backed datasets do NOT need this: the shared partition log
    IS the replicated stream (one produce, every replica consumes at
    its own offset) — exactly the reference's Kafka model."""

    def __init__(self, dataset: str, mapper: ShardMapper,
                 publish_for_node: Mapping[str, Callable[[int, bytes], None]],
                 local_node: Optional[str] = None,
                 max_queued_per_peer: int = 1024):
        self.dataset = dataset
        self.mapper = mapper
        self.publish_for_node = dict(publish_for_node)
        self.local_node = local_node
        self.max_queued_per_peer = max_queued_per_peer
        self._closed = False
        self._episodes = _FailureEpisodes(dataset)
        # shards currently dropping because every copy is terminal —
        # gates the once-per-episode flight event
        self._dropping_shards: set = set()
        self._lanes: dict[str, _ReplicaLane] = {}
        self._lane_lock = threading.Lock()

    def _lane(self, node: str) -> Optional[_ReplicaLane]:
        with self._lane_lock:
            if self._closed:
                return None
            lane = self._lanes.get(node)
            if lane is None:
                lane = self._lanes[node] = _ReplicaLane(
                    self.dataset, node, self.publish_for_node[node],
                    self.max_queued_per_peer, self._episodes)
            return lane

    def __call__(self, shard: int, container: bytes) -> int:
        """Publish to every LIVE replica; returns deliveries that
        succeeded synchronously or were accepted into a peer lane.
        Terminal Down/Error copies are skipped — a permanently-dead
        peer must not pin a full lane and burn a connect attempt +
        failure event per container forever; it rejoins via checkpoint
        replay (broker) or accepts its divergence (queue transport,
        doc/ha.md)."""
        if self._closed:
            return 0
        # STOPPED joins Down/Error here: an operator-stopped replica's
        # ingestion consumer is not running (runnable_shards_for_node),
        # so delivering to it would buffer containers into an unbounded
        # queue nothing drains until OOM
        skip = (ShardStatus.DOWN, ShardStatus.ERROR, ShardStatus.STOPPED)
        nodes = [r.node for r in self.mapper.replicas(shard)
                 if r.status not in skip]
        if not nodes:
            if self.local_node is not None \
                    and not self.mapper.replicas(shard):
                # shard not assigned ANYWHERE yet (startup): keep data
                # flowing locally.  An assigned group that is all-
                # terminal is NOT rerouted here — buffering into a
                # queue no local consumer drains would grow unboundedly
                # and the copies rejoin from their own checkpoints,
                # never from this queue
                nodes = [self.local_node]
            else:
                # EVERY assigned copy is terminal: the container is
                # dropped.  One counter inc per container (total loss
                # must be measurable), one flight event per episode
                # (heavy ingest must not flood the ring)
                _METRICS["replica_publish_failures"].inc(
                    dataset=self.dataset, node="(all-terminal)")
                if shard not in self._dropping_shards:
                    self._dropping_shards.add(shard)
                    from filodb_tpu.utils.devicewatch import FLIGHT
                    FLIGHT.record("ingest.replica_publish_failed",
                                  dataset=self.dataset, shard=shard,
                                  node="(all-terminal)",
                                  error="every replica is Down/Error/"
                                        "Stopped — containers dropped")
                return 0
        self._dropping_shards.discard(shard)
        delivered = 0
        for node in nodes:
            pub = self.publish_for_node.get(node)
            if pub is None:
                self._episodes.fail(node, shard,
                                    "no transport configured for "
                                    "this replica's node")
                continue
            if node == self.local_node:
                try:
                    pub(shard, container)
                    delivered += 1
                    _METRICS["replica_publishes"].inc(
                        dataset=self.dataset, node=node)
                    self._episodes.ok(node)
                except Exception as e:  # noqa: BLE001 — local queue gone
                    self._episodes.fail(node, shard, str(e))
            else:
                lane = self._lane(node)
                if lane is not None and lane.enqueue(shard, container):
                    delivered += 1
        return delivered

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait for every peer lane to empty (tests/shutdown)."""
        with self._lane_lock:
            lanes = list(self._lanes.values())
        return all(lane.drain(timeout_s) for lane in lanes)

    def close(self) -> None:
        """Stop every peer lane (undelivered containers are dropped)
        and refuse further publishes.  Wired into FiloServer.shutdown —
        without it a 'killed' in-process node's lanes would keep
        POSTing buffered containers to surviving peers."""
        with self._lane_lock:
            self._closed = True
            lanes = list(self._lanes.values())
            self._lanes.clear()
        for lane in lanes:
            lane.close()


def http_container_push(endpoint: str, dataset: str,
                        timeout_s: float = 5.0
                        ) -> Callable[[int, bytes], None]:
    """A per-node publish callable shipping containers to a peer's
    ``POST /ingest/<dataset>/<shard>`` edge (the queue-transport leg of
    the dual-write fanout; broker transports never need it)."""
    import urllib.request
    base = endpoint.rstrip("/")

    def push(shard: int, container: bytes) -> None:
        req = urllib.request.Request(
            f"{base}/ingest/{dataset}/{shard}", data=container,
            method="POST",
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=timeout_s):
            pass

    return push


class GatewayServer:
    """TCP server speaking Influx line protocol, one line per record
    (reference: GatewayServer Netty pipeline)."""

    def __init__(self, publisher: ShardingPublisher, host: str = "127.0.0.1",
                 port: int = 0, flush_every: int = 128):
        self.publisher = publisher
        self.host = host
        self.port = port
        self.flush_every = flush_every
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        gw = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                # batch lines so the COLUMNAR ingest path serves the
                # wire traffic too (per-line ingest pays per-line parse
                # + lock overhead — the cost the columnar path removes)
                buf: list[str] = []
                for raw in self.rfile:
                    buf.append(raw.decode("utf-8", "replace"))
                    if len(buf) >= gw.flush_every:
                        gw.publisher.ingest_influx_batch("".join(buf))
                        buf.clear()
                        gw.publisher.flush()
                if buf:
                    gw.publisher.ingest_influx_batch("".join(buf))
                gw.publisher.flush()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # scoped here, not on the stdlib class

        self._server = _Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="gateway", daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
