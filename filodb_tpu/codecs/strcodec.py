"""UTF-8 string and nbit-int vector codecs.

Capability match for the reference's UTF8Vector / DictUTF8Vector /
IntBinaryVector (reference: memory/src/main/scala/filodb.memory/format/
UTF8Vector.scala:17, DictUTF8Vector.scala:15, vectors/IntBinaryVector.scala:15).
Used by tag columns and multi-column event schemas (the GDELT-style use case).
"""

from __future__ import annotations

import struct

import numpy as np

from filodb_tpu.codecs.wire import WireType

_N = struct.Struct("<I")


def encode_utf8(strings: list[bytes | str]) -> bytes:
    """Dense layout: offsets (u32[n+1]) + concatenated payload.  If the
    distinct-value ratio is low, dictionary-encode instead (reference's
    DictUTF8Vector auto-selection in optimize())."""
    bs = [s.encode() if isinstance(s, str) else s for s in strings]
    uniq = sorted(set(bs))
    if len(bs) >= 8 and len(uniq) * 2 <= len(bs):
        index = {s: i for i, s in enumerate(uniq)}
        codes = np.array([index[s] for s in bs], dtype=np.uint32)
        dict_blob = encode_utf8_dense(uniq)
        return (bytes([WireType.DICT_UTF8]) + _N.pack(len(bs)) + _N.pack(len(dict_blob))
                + dict_blob + encode_nbit(codes))
    return encode_utf8_dense(bs)


def encode_utf8_dense(bs: list[bytes]) -> bytes:
    offsets = np.zeros(len(bs) + 1, dtype=np.uint32)
    np.cumsum([len(b) for b in bs], out=offsets[1:])
    return (bytes([WireType.UTF8_DENSE]) + _N.pack(len(bs))
            + offsets.astype("<u4").tobytes() + b"".join(bs))


def decode_utf8(buf: bytes) -> list[bytes]:
    wire = buf[0]
    if wire == WireType.UTF8_DENSE:
        (n,) = _N.unpack_from(buf, 1)
        offs = np.frombuffer(buf, dtype="<u4", count=n + 1, offset=5)
        base = 5 + 4 * (n + 1)
        return [bytes(buf[base + offs[i]:base + offs[i + 1]]) for i in range(n)]
    if wire == WireType.DICT_UTF8:
        (n,) = _N.unpack_from(buf, 1)
        (dlen,) = _N.unpack_from(buf, 5)
        uniq = decode_utf8(buf[9:9 + dlen])
        codes = decode_nbit(buf[9 + dlen:])
        return [uniq[c] for c in codes]
    raise ValueError(f"not a UTF8 vector: wire type {wire}")


def encode_nbit(values: np.ndarray) -> bytes:
    """nbits-packed unsigned ints (1/2/4/8/16/32 bits per value)."""
    v = np.ascontiguousarray(values, dtype=np.uint32)
    maxv = int(v.max()) if len(v) else 0
    for nbits in (1, 2, 4, 8, 16, 32):
        if maxv < (1 << nbits):
            break
    out = bytearray([WireType.INT_NBIT, nbits])
    out += _N.pack(len(v))
    if nbits >= 8:
        out += v.astype(f"<u{nbits // 8}").tobytes()
    else:
        per_byte = 8 // nbits
        pad = (-len(v)) % per_byte
        vp = np.concatenate([v, np.zeros(pad, dtype=np.uint32)]).reshape(-1, per_byte)
        packed = np.zeros(len(vp), dtype=np.uint32)
        for k in range(per_byte):
            packed |= vp[:, k] << (k * nbits)
        out += packed.astype(np.uint8).tobytes()
    return bytes(out)


def decode_nbit(buf: bytes) -> np.ndarray:
    if buf[0] != WireType.INT_NBIT:
        raise ValueError(f"not an nbit vector: wire type {buf[0]}")
    nbits = buf[1]
    (n,) = _N.unpack_from(buf, 2)
    payload = buf[6:]
    if nbits >= 8:
        return np.frombuffer(payload, dtype=f"<u{nbits // 8}", count=n).astype(np.uint32)
    per_byte = 8 // nbits
    raw = np.frombuffer(payload, dtype=np.uint8, count=(n + per_byte - 1) // per_byte)
    mask = (1 << nbits) - 1
    out = np.empty(len(raw) * per_byte, dtype=np.uint32)
    for k in range(per_byte):
        out[k::per_byte] = (raw >> (k * nbits)) & mask
    return out[:n]
