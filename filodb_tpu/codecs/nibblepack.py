"""Predictive NibblePack codec.

Implements the public NibblePack storage scheme described in the reference's
compression spec (reference: doc/compression.md:33-76 and
memory/src/main/scala/filodb.memory/format/NibblePack.scala:12): u64 values are
packed 8 at a time; each group stores

    +0  u8  bitmask, bit i set => value i is nonzero
    +1  u8  (only if bitmask != 0)
            bits 0-3: number of trailing zero *nibbles* (0-15)
            bits 4-7: number of stored nibbles - 1   (0-15)
    +2  nibble stream: for each nonzero value in bitmask order, the
        ``numNibbles`` middle nibbles, least-significant nibble first,
        packed two-per-byte (low nibble first).

This is a fresh numpy implementation of that format (plus zigzag helpers for
signed residual streams).  A C++ fast path with identical output lives in
``filodb_tpu/native``; :func:`use_native` toggles it when built.
"""

from __future__ import annotations

import numpy as np

_native = None  # set by filodb_tpu.native when the shared lib is importable


def zigzag_encode(values: np.ndarray) -> np.ndarray:
    """Map signed int64 -> unsigned u64 with small magnitudes near zero."""
    v = values.astype(np.int64, copy=False)
    return ((v.astype(np.uint64) << np.uint64(1)) ^ (v >> np.int64(63)).astype(np.uint64))


def zigzag_decode(values: np.ndarray) -> np.ndarray:
    u = values.astype(np.uint64, copy=False)
    return ((u >> np.uint64(1)).astype(np.int64)) ^ -(u & np.uint64(1)).astype(np.int64)


def _nibble_widths(group: np.ndarray) -> tuple[int, int, int]:
    """Return (bitmask, trailing_zero_nibbles, num_nibbles) for one group of 8."""
    nz = group != 0
    bitmask = int(np.packbits(nz[::-1]).item())  # bit i corresponds to value i
    if bitmask == 0:
        return 0, 0, 0
    vals = group[nz]
    # leading/trailing zero bit counts over nonzero values only (zero values
    # would contribute 64 and never win the min)
    tz_bits = 64
    lz_bits = 64
    for v in vals:
        iv = int(v)
        tz_bits = min(tz_bits, (iv & -iv).bit_length() - 1)
        lz_bits = min(lz_bits, 64 - iv.bit_length())
    trailing_nibbles = tz_bits // 4
    leading_nibbles = lz_bits // 4
    num_nibbles = max(1, 16 - leading_nibbles - trailing_nibbles)
    return bitmask, trailing_nibbles, num_nibbles


def pack(values: np.ndarray) -> bytes:
    """NibblePack an array of u64.  Length is NOT stored; callers record it."""
    if _native is not None:
        return _native.nibble_pack(np.ascontiguousarray(values, dtype=np.uint64))
    v = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(v)
    ngroups = (n + 7) // 8
    padded = np.zeros(ngroups * 8, dtype=np.uint64)
    padded[:n] = v
    out = bytearray()
    for g in range(ngroups):
        group = padded[g * 8:(g + 1) * 8]
        bitmask, trailing, num_nibbles = _nibble_widths(group)
        out.append(bitmask)
        if bitmask == 0:
            continue
        out.append((trailing & 0xF) | ((num_nibbles - 1) << 4))
        # emit nibbles LSB-first for each nonzero value
        nibbles = []
        for v64 in group[group != 0]:
            shifted = int(v64) >> (trailing * 4)
            for k in range(num_nibbles):
                nibbles.append((shifted >> (4 * k)) & 0xF)
        if len(nibbles) % 2:
            nibbles.append(0)
        for lo, hi in zip(nibbles[::2], nibbles[1::2]):
            out.append(lo | (hi << 4))
    return bytes(out)


def unpack(buf: bytes, count: int, offset: int = 0) -> tuple[np.ndarray, int]:
    """Decode ``count`` u64 values starting at ``offset``.

    Returns (values, next_offset).
    """
    if _native is not None:
        return _native.nibble_unpack(buf, count, offset)
    out = np.zeros(((count + 7) // 8) * 8, dtype=np.uint64)
    pos = offset
    mv = memoryview(buf)
    for g in range((count + 7) // 8):
        bitmask = mv[pos]
        pos += 1
        if bitmask == 0:
            continue
        hdr = mv[pos]
        pos += 1
        trailing = hdr & 0xF
        num_nibbles = (hdr >> 4) + 1
        nnz = bin(bitmask).count("1")
        total_nibbles = num_nibbles * nnz
        nbytes = (total_nibbles + 1) // 2
        chunk = mv[pos:pos + nbytes]
        pos += nbytes
        # expand nibble stream
        nibbles = np.empty(nbytes * 2, dtype=np.uint64)
        arr = np.frombuffer(chunk, dtype=np.uint8)
        nibbles[0::2] = arr & 0xF
        nibbles[1::2] = arr >> 4
        vi = 0
        for i in range(8):
            if bitmask & (1 << i):
                val = 0
                base = vi * num_nibbles
                for k in range(num_nibbles):
                    val |= int(nibbles[base + k]) << (4 * k)
                out[g * 8 + i] = np.uint64((val << (trailing * 4)) & 0xFFFFFFFFFFFFFFFF)
                vi += 1
    return out[:count], pos


def packed_end(buf: bytes, count: int, offset: int = 0) -> int:
    """Return the end offset of a packed run without materializing values."""
    if _native is not None:
        return _native.nibble_packed_end(buf, count, offset)
    pos = offset
    mv = memoryview(buf)
    for _ in range((count + 7) // 8):
        bitmask = mv[pos]
        pos += 1
        if bitmask == 0:
            continue
        hdr = mv[pos]
        pos += 1
        num_nibbles = (hdr >> 4) + 1
        nnz = bin(bitmask).count("1")
        pos += (num_nibbles * nnz + 1) // 2
    return pos
