"""XOR-class grid codec: the compressed-resident value-plane layout.

This is the encode side of the device grid's compressed residents
(memstore/devicestore.py) and the layout contract the fused serving
kernels (ops/grid.py ``rate_grid_packed``) rely on.  It is the Gorilla
XOR-with-previous idea restated with STATIC shapes so XLA/Mosaic can
vectorize the decode (reference: queries read compressed BinaryVectors
straight from block memory, BlockManager.scala:142, doc/compression.md):

- Per lane, residual ``r`` holds ``bits[r] ^ bits[r-1]``; row 0's
  residual is stored as 0 and the full first value rides a separate
  ``first`` plane (one big row-0 residual must not widen a lane's
  class).
- Each lane is classified by the fixed width (8/16[/32] bits) that
  holds ALL its residuals after a per-lane right shift by the common
  trailing-zero count; incompressible lanes stay raw (residual form,
  bit-preserving).
- Lanes are grouped by class into contiguous sub-planes (``p8``/
  ``p16``[/``p32``]/``raw``), so decode is widen -> shift -> one
  log2(B) prefix-XOR scan down the bucket axis -> bitcast, uniformly
  across every class; ``inv`` gathers lane order back.

Layout guarantees the fused TPU kernel relies on (NEW vs the round-5
in-devicestore packer):

1. **Lane-block alignment** — every class sub-plane's lane count is a
   multiple of ``lane_block`` (default 128, the Mosaic lane tile), via
   the cheaper of promoting excess lanes to the next-wider class or
   padding with zero lanes (zero residuals + first 0.0 decode to a
   constant 0.0 column; consumers drop pad lanes through ``inv`` /
   group maps).  The widest (raw) plane can only pad.
2. **Per-plane meta tiles** (f32 planes only) — ``m8``/``m16``/
   ``mraw``: ``[8, n]`` int32 with row 0 = per-lane shift, row 1 = the
   first-row value's bits, row 2 = per-lane within-bucket phase (for
   the uniform-phase kernels; 1 when unknown), rows 3-7 zero.  8 rows
   because Mosaic DMAs sublane multiples; the kernel reads one meta
   tile next to each packed tile, so decode needs no second input
   stream per quantity.
3. **Plane order is packed order** — consumers compose their existing
   lane indirections (request lane index, group map, phase row) with
   ``inv`` (original lane -> packed position) host-side; the device
   never gathers.

``unpack_vals`` is the bit-exact CPU decode used as the oracle for the
fused kernel's equivalence sweep (tests/test_packed_kernel.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

LANE_BLOCK = 128          # Mosaic lane-tile granularity every plane honors

_DTS = {8: np.uint8, 16: np.uint16, 32: np.uint32}


class PackedVals(NamedTuple):
    """One packed value plane.

    ``planes`` holds everything the device needs (class planes, shift/
    first/meta planes, ``inv``); ``inv`` rides separately as host
    metadata too (original lane -> packed position, int64) so callers
    can compose lane indirections without a device readback.
    ``nbytes`` is the resident footprint (sum of plane bytes)."""

    planes: dict
    inv: np.ndarray
    nbytes: int


def _ctz_blen(res: np.ndarray, word) -> tuple[np.ndarray, np.ndarray]:
    """Per-lane common trailing zeros of the OR-reduced residuals and
    the significant bit length after that shift."""
    L = res.shape[1]
    orv = np.bitwise_or.reduce(res, axis=0)
    nz = orv != 0
    low = orv & (~orv + word(1))
    ctz = np.zeros(L, np.int64)
    ctz[nz] = np.log2(low[nz].astype(np.float64)).astype(np.int64)
    shifted = orv >> ctz.astype(word)
    blen = np.zeros(L, np.int64)
    m = shifted.copy()
    while (m > 0).any():
        blen[m > 0] += 1
        m >>= word(1)
    return ctz, blen


# a plane this narrow may skip lane-block alignment: the fused kernel
# runs it as ONE whole-plane block (Mosaic masks sub-tile lane dims),
# and the VMEM footprint of a [B, <=1024] tile stays small.  Wider
# planes must align so the kernel can tile/pipeline them.
UNPADDED_MAX = 1024


def _align_classes(by_cls: list[list], widths: tuple, itemsize: int,
                   B: int, lane_block: int, stride: int = 1) -> list[int]:
    """Enforce guarantee 1: each class's lane count is either a
    multiple of ``lane_block`` or small enough (<= UNPADDED_MAX) to run
    as one whole-plane kernel block.  Misaligned classes take one of:
    promote the excess to the next-wider class (a narrow residual
    always fits a wider word), pad with zero lanes, or stay as-is when
    narrow.  With <= 4 classes the <= 3^4 decision combinations are
    searched exhaustively for the minimum resident bytes — a one-step
    greedy misjudges cascades (promoting into an empty raw plane would
    force an expensive raw pad).  Mutates ``by_cls`` (last slot = raw);
    returns per-class pad lane counts.

    ``stride > 1`` (histogram bucket planes) disables promotion: a
    promotable excess is rarely a whole number of ``stride``-column
    series AND congruent to the misalignment, and splitting one
    series' bucket columns across class planes would break the
    bucket-contiguity guarantee the hist kernels slice by.  Pads are
    appended zero lanes (never part of a series), so padding stays
    legal at any stride."""
    import itertools

    nbytes_of = [w // 8 for w in widths] + [itemsize]
    nc = len(by_cls)

    def simulate(choices: tuple):
        counts = [len(c) for c in by_cls]
        pads = [0] * nc
        promotes = [0] * nc
        for i in range(nc):
            rem = counts[i] % lane_block
            if rem == 0:
                continue
            pick = choices[i]
            if pick == "asis" and counts[i] > UNPADDED_MAX:
                pick = "pad"     # too wide to run unaligned
            if pick == "promote" and (i == nc - 1 or stride > 1):
                pick = "pad"     # nothing wider than raw / hist contiguity
            if pick == "promote":
                counts[i + 1] += rem
                counts[i] -= rem
                promotes[i] = rem
            elif pick == "pad":
                pads[i] = lane_block - rem
        total = sum((counts[i] + pads[i]) * nbytes_of[i] * B
                    for i in range(nc))
        return total, pads, promotes

    best = min((simulate(c) for c in
                itertools.product(("promote", "pad", "asis"), repeat=nc)),
               key=lambda t: t[0])
    _total, pads, promotes = best
    for i in range(nc - 1):
        if promotes[i]:
            by_cls[i + 1] = by_cls[i][-promotes[i]:] + by_cls[i + 1]
            del by_cls[i][-promotes[i]:]
    return pads


def pack_vals(vals: np.ndarray, lane_block: int = LANE_BLOCK,
              phase: Optional[np.ndarray] = None,
              min_width: int = 0, stride: int = 1) -> Optional[PackedVals]:
    """Pack a ``[B, L]`` f32/f64 value plane into XOR-class form.

    Returns None when compression doesn't pay (packed footprint must
    save >= 25% vs the raw value plane).  ``phase`` ([L] int32
    within-bucket scrape offsets, original lane order) rides into the
    meta tiles for the uniform-phase kernels; omit when unknown.
    ``min_width`` forces lanes that would classify narrower up to the
    given class — a workload whose residuals provably fit one width
    (e.g. the north-star integer counters) then packs as a SINGLE class
    plane, which preserves lane (and therefore group) order for the
    fused grouped kernel's contiguity contract.

    ``stride`` (histogram bucket planes, devicestore's group-slot
    layout ``hist_slot_garr``: column ``s*stride + j`` = series s,
    cumulative bucket j) packs at SERIES granularity: all ``stride``
    columns of a series classify together (widest bucket column wins)
    and stay CONTIGUOUS, in bucket order, in the packed layout — the
    guarantee the fused hist kernels (ops/grid.py
    ``hist_grid_grouped_packed``) rely on to reduce the bucket
    dimension with banded matmuls.  ``unpack_vals`` stays bit-exact
    for every stride."""
    B, L = vals.shape
    if B == 0 or L == 0:
        return None
    if stride > 1 and L % stride != 0:
        raise ValueError(f"plane width {L} not a multiple of the "
                         f"bucket stride {stride}")
    itemsize = vals.dtype.itemsize
    word = np.uint32 if itemsize == 4 else np.uint64
    bits = np.ascontiguousarray(vals).view(word)
    res = bits.copy()
    res[1:] ^= bits[:-1]
    # row 0's residual is the full first value (no predecessor) — store
    # it as its own plane so one big residual can't push a whole lane
    # out of its narrow class
    res[0] = 0
    ctz, blen = _ctz_blen(res, word)
    widths = (8, 16, 32) if itemsize == 8 else (8, 16)
    if stride > 1:
        # series-granular classification: the widest bucket column of a
        # series classifies all of its columns, so the series' bucket
        # columns can never straddle a class boundary
        blen = np.repeat(blen.reshape(-1, stride).max(axis=1), stride)
    cls = np.full(L, len(widths), np.int64)            # widest = raw
    for i, w in enumerate(reversed(widths)):
        cls[blen <= w] = len(widths) - 1 - i
    if min_width:
        floor = widths.index(min_width)
        cls[cls < floor] = floor
    by_cls = [list(np.flatnonzero(cls == i)) for i in range(len(widths))]
    by_cls.append(list(np.flatnonzero(cls == len(widths))))   # raw
    pads = _align_classes(by_cls, widths, itemsize, B, lane_block,
                          stride=stride)
    # canonical order: ascending original lane within each class, so a
    # single-class pack is the IDENTITY permutation (the group-aligned
    # contract rate_grid_grouped_packed relies on)
    by_cls = [sorted(c) for c in by_cls]
    planes: dict[str, np.ndarray] = {}
    order_parts: list[np.ndarray] = []
    first_parts: list[np.ndarray] = []
    meta = itemsize == 4                 # fused kernels are f32-only
    for i, key in enumerate([f"p{w}" for w in widths] + ["raw"]):
        lanes_i = np.asarray(by_cls[i], dtype=np.int64)
        n = len(lanes_i) + pads[i]
        if n == 0:
            continue
        zl = np.zeros(n, np.int32)
        if key != "raw":          # raw residuals are stored UNSHIFTED
            zl[:len(lanes_i)] = ctz[lanes_i].astype(np.int32)
        fl = np.zeros(n, vals.dtype)
        fl[:len(lanes_i)] = vals[0, lanes_i]
        if key == "raw":
            # raw lanes store RESIDUALS too (float-viewed, bit-
            # preserving): ONE prefix-XOR scan decodes every class
            arr = np.zeros((B, n), word)
            arr[:, :len(lanes_i)] = res[:, lanes_i]
            planes["raw"] = arr.view(vals.dtype)
        else:
            w = widths[i]
            arr = np.zeros((B, n), _DTS[w])
            arr[:, :len(lanes_i)] = (res[:, lanes_i]
                                     >> ctz[lanes_i].astype(word))
            planes[key] = arr
            planes[f"z{w}"] = zl
        if meta:
            m = np.zeros((8, n), np.int32)
            m[0] = zl
            m[1, :len(lanes_i)] = np.ascontiguousarray(
                vals[0, lanes_i].astype(np.float32)).view(np.int32)
            m[2] = 1
            if phase is not None:
                m[2, :len(lanes_i)] = np.asarray(phase,
                                                 np.int32)[lanes_i]
            planes["mraw" if key == "raw" else f"m{w}"] = m
        order_parts.append(np.concatenate(
            [lanes_i, np.full(pads[i], -1, np.int64)]))
        first_parts.append(fl)
    if "raw" not in planes:
        # dtype marker for consumers that introspect the packed word
        # size; also keeps decode uniform (empty plane concatenates away)
        planes["raw"] = np.zeros((B, 0), vals.dtype)
    order = np.concatenate(order_parts)
    planes["first"] = np.concatenate(first_parts)
    inv = np.full(L, -1, np.int64)
    inv[order[order >= 0]] = np.flatnonzero(order >= 0)
    planes["inv"] = inv.astype(np.int32)
    nbytes = sum(a.nbytes for a in planes.values())
    if nbytes * 4 > B * L * itemsize * 3:              # must save >= 25%
        return None
    return PackedVals(planes, inv, nbytes)


def unpack_vals(packed: PackedVals | dict) -> np.ndarray:
    """Bit-exact CPU decode of :func:`pack_vals` output back to the
    original ``[B, L]`` plane — the oracle the fused on-device decode
    must match bit-for-bit."""
    planes = packed.planes if isinstance(packed, PackedVals) else packed
    raw = np.asarray(planes["raw"])
    itemsize = raw.dtype.itemsize
    word = np.uint32 if itemsize == 4 else np.uint64
    parts = []
    for w in (8, 16, 32):
        p = planes.get(f"p{w}")
        if p is None:
            continue
        z = np.asarray(planes[f"z{w}"]).astype(word)
        parts.append(np.asarray(p).astype(word) << z[None, :])
    if raw.shape[1]:
        parts.append(np.ascontiguousarray(raw).view(word))
    u = np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    u = np.bitwise_xor.accumulate(u, axis=0)
    first = np.ascontiguousarray(np.asarray(planes["first"])).view(word)
    u = u ^ first[None, :]
    vals = u.view(raw.dtype)
    inv = np.asarray(planes["inv"])
    return vals[:, inv]
