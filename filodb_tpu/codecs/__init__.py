"""Columnar codecs: the equivalent of the reference's ``memory/format`` layer.

The reference implements off-heap BinaryVectors with per-row appenders and
readers (reference: memory/src/main/scala/filodb.memory/format/BinaryVector.scala).
Here the unit of work is a whole numpy array: encoders take dense arrays and
produce compact ``bytes``; decoders take ``bytes`` and produce dense arrays
ready to be stacked into device tensors.  Hot codecs have a C++ fast path
(filodb_tpu/native) with these numpy implementations as the reference/fallback.
"""

import os

from filodb_tpu.codecs.wire import WireType  # noqa: F401
from filodb_tpu.codecs import nibblepack, deltadelta, doublecodec  # noqa: F401

if os.environ.get("FILODB_TPU_NATIVE", "1") != "0":
    try:
        from filodb_tpu import native as _native_mod

        _native_mod.enable()
    except Exception:  # no compiler / load failure: numpy paths keep working
        pass
