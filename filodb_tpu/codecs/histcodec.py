"""Histogram vector codec: 2D-delta NibblePacked sections.

Capability match for the reference's section-based HistogramVector
(reference: memory/src/main/scala/filodb.memory/format/vectors/
HistogramVector.scala:189, Section.scala, doc/compression.md "2D Delta
Compression"): rows are cumulative bucket counts; row 0 of each section is
stored as within-row deltas, subsequent rows as deltas vs the previous row —
both streams zigzag'd and NibblePacked.  Sections bound how many rows a
decoder must replay, standing in for the reference's skippable section
headers.

Layout:
    u8   WireType.HIST_2D_DELTA
    u32  n_rows
    u16  n_buckets
    u16  rows_per_section
    [bucket scheme: HistogramBuckets.serialize()]
    per section:  u32 payload_bytes, then NibblePacked payload
"""

from __future__ import annotations

import struct

import numpy as np

from filodb_tpu.codecs import nibblepack
from filodb_tpu.codecs.wire import WireType
from filodb_tpu.core.histogram import HistogramBuckets

_HDR = struct.Struct("<IHH")
DEFAULT_ROWS_PER_SECTION = 64


def encode(buckets: HistogramBuckets, rows: np.ndarray,
           rows_per_section: int = DEFAULT_ROWS_PER_SECTION) -> bytes:
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    n_rows, n_buckets = rows.shape
    out = bytearray([WireType.HIST_2D_DELTA])
    out += _HDR.pack(n_rows, n_buckets, rows_per_section)
    out += buckets.serialize()
    for start in range(0, n_rows, rows_per_section):
        sect = rows[start:start + rows_per_section]
        deltas = np.empty_like(sect)
        # row 0: within-row delta of cumulative buckets (small non-negative)
        deltas[0, 0] = sect[0, 0]
        deltas[0, 1:] = np.diff(sect[0])
        # rows 1..: 2D delta vs previous row
        deltas[1:] = sect[1:] - sect[:-1]
        payload = nibblepack.pack(nibblepack.zigzag_encode(deltas.ravel()))
        out += struct.pack("<I", len(payload))
        out += payload
    return bytes(out)


def decode(buf: bytes) -> tuple[HistogramBuckets, np.ndarray]:
    if buf[0] != WireType.HIST_2D_DELTA:
        raise ValueError(f"not a histogram vector: wire type {buf[0]}")
    n_rows, n_buckets, rps = _HDR.unpack_from(buf, 1)
    buckets, pos = HistogramBuckets.deserialize(buf, 1 + _HDR.size)
    rows = np.empty((n_rows, n_buckets), dtype=np.int64)
    for start in range(0, n_rows, rps):
        count = min(rps, n_rows - start)
        (nbytes,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        packed, _ = nibblepack.unpack(buf, count * n_buckets, pos)
        pos += nbytes
        deltas = nibblepack.zigzag_decode(packed).reshape(count, n_buckets)
        sect = np.empty_like(deltas)
        sect[0] = np.cumsum(deltas[0])
        for r in range(1, count):
            sect[r] = sect[r - 1] + deltas[r]
        rows[start:start + count] = sect
    return buckets, rows


def num_values(buf: bytes) -> int:
    return _HDR.unpack_from(buf, 1)[0]


# --------------------------------------------------------------------------
# Single-sample blob: the ingest wire form of one histogram
# --------------------------------------------------------------------------

def encode_hist_value(buckets: HistogramBuckets, values) -> bytes:
    """One histogram sample as a self-describing blob — the BinaryHistogram
    that rides inside ingest records (reference: memory/format/vectors/
    HistogramVector.scala:34 BinHistogram layout: bucket scheme + packed
    cumulative counts)."""
    vals = np.ascontiguousarray(values, dtype=np.int64)
    out = bytearray([WireType.HIST_BLOB])
    out += struct.pack("<H", len(vals))
    out += buckets.serialize()
    deltas = np.empty_like(vals)
    if len(vals):
        deltas[0] = vals[0]
        deltas[1:] = np.diff(vals)
    out += nibblepack.pack(nibblepack.zigzag_encode(deltas))
    return bytes(out)


def decode_hist_value(buf: bytes) -> tuple[HistogramBuckets, np.ndarray]:
    if buf[0] != WireType.HIST_BLOB:
        raise ValueError(f"not a histogram blob: wire type {buf[0]}")
    (n,) = struct.unpack_from("<H", buf, 1)
    buckets, pos = HistogramBuckets.deserialize(buf, 3)
    deltas, _ = nibblepack.unpack(buf, n, pos)
    return buckets, np.cumsum(nibblepack.zigzag_decode(deltas))
