"""Delta-delta (DELTA2) codec for int64 timestamp/counter vectors.

Models the vector as a sloped line ``pred[i] = base + slope*i`` and stores
only the zigzag'd residuals, nibble-packed — the same sloped-line model the
reference uses for timestamps and long counters (reference:
memory/src/main/scala/filodb.memory/format/vectors/DeltaDeltaVector.scala:28,
doc/compression.md "Long/Integer Compression").  Perfectly linear vectors
(regular timestamps, idle counters) collapse to a 21-byte const encoding.

Layout (after the 1-byte WireType header written by the caller):

    u32  n          number of values
    i64  base       value of element 0 in the line model
    i64  slope      per-step increment
    [nibble-packed zigzag residuals]     (DELTA2 only; absent for CONST_LONG)
"""

from __future__ import annotations

import struct

import numpy as np

from filodb_tpu.codecs import nibblepack
from filodb_tpu.codecs.wire import WireType

_HDR = struct.Struct("<Iqq")

_native = None  # set by filodb_tpu.native when the shared lib is importable
_native_enc = None  # batch-encode hook (flush/downsample hot loop)


def encode_batch(arrays) -> list[bytes]:
    """Encode many int64 vectors; ONE native call when available (the
    per-vector Python overhead dominates small downsample chunks)."""
    if _native_enc is not None:
        return _native_enc.ll_encode_batch(arrays)
    return [encode(a) for a in arrays]


def encode(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    if n == 0:
        return bytes([WireType.CONST_LONG]) + _HDR.pack(0, 0, 0)
    base = int(v[0])
    slope = int(round((int(v[-1]) - base) / (n - 1))) if n > 1 else 0
    # wrap slope into int64: residual arithmetic is modular (2^64) on both
    # encode and decode, so wraparound round-trips exactly even for vectors
    # spanning the full int64 range
    slope = (slope + 2**63) % 2**64 - 2**63
    with np.errstate(over="ignore"):
        pred = np.int64(base) + np.int64(slope) * np.arange(n, dtype=np.int64)
        resid = v - pred
    if not resid.any():
        return bytes([WireType.CONST_LONG]) + _HDR.pack(n, base, slope)
    packed = nibblepack.pack(nibblepack.zigzag_encode(resid))
    return bytes([WireType.DELTA2]) + _HDR.pack(n, base, slope) + packed


def decode(buf: bytes) -> np.ndarray:
    wire = buf[0]
    if wire not in (WireType.CONST_LONG, WireType.DELTA2):
        raise ValueError(f"not a DELTA2 vector: wire type {wire}")
    if _native is not None:
        return _native.dd_decode(buf)
    n, base, slope = _HDR.unpack_from(buf, 1)
    with np.errstate(over="ignore"):
        line = np.int64(base) + np.int64(slope) * np.arange(n, dtype=np.int64)
        if wire == WireType.CONST_LONG:
            return line
        packed, _ = nibblepack.unpack(buf, n, 1 + _HDR.size)
        return line + nibblepack.zigzag_decode(packed)


def num_values(buf: bytes) -> int:
    return _HDR.unpack_from(buf, 1)[0]
