"""Float64 vector codec.

Strategy mirrors the reference's DoubleVector optimizer (reference:
memory/src/main/scala/filodb.memory/format/vectors/DoubleVector.scala:14):

- all values integral and line-like  -> route through the DELTA2 long codec
  (``DELTA2_DOUBLE``), the common case for counters ingested as doubles;
- constant vectors -> ``CONST_DOUBLE``;
- otherwise -> Gorilla-style previous-value XOR predictor whose u64 residual
  stream is NibblePacked (``XOR_DOUBLE``; doc/compression.md "Floating Point
  Compression" lists XOR as the predictor feeding NibblePack).

NaN is used by ingestion as the "no data" sentinel, exactly like the
reference's Prometheus schemas; NaNs survive round-trip bit-exactly through
the XOR path.
"""

from __future__ import annotations

import struct

import numpy as np

from filodb_tpu.codecs import deltadelta, nibblepack
from filodb_tpu.codecs.wire import WireType

_N = struct.Struct("<I")

_native = None  # set by filodb_tpu.native when the shared lib is importable


def encode(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.float64)
    n = len(v)
    if (n and np.isfinite(v).all() and (np.abs(v) < 2**63).all()
            and not (np.signbit(v) & (v == 0)).any()):  # -0.0 must keep its sign bit
        as_int = v.astype(np.int64)
        if (as_int.astype(np.float64) == v).all():
            inner = deltadelta.encode(as_int)
            return bytes([WireType.DELTA2_DOUBLE]) + inner
    if n and np.all(v[0] == v) and not np.isnan(v[0]):
        return bytes([WireType.CONST_DOUBLE]) + _N.pack(n) + struct.pack("<d", v[0])
    bits = v.view(np.uint64)
    prev = np.concatenate([[np.uint64(0)], bits[:-1]])
    residuals = bits ^ prev
    return bytes([WireType.XOR_DOUBLE]) + _N.pack(n) + nibblepack.pack(residuals)


def decode(buf: bytes) -> np.ndarray:
    wire = buf[0]
    if wire == WireType.DELTA2_DOUBLE:
        return deltadelta.decode(buf[1:]).astype(np.float64)
    if wire == WireType.CONST_DOUBLE:
        (n,) = _N.unpack_from(buf, 1)
        (val,) = struct.unpack_from("<d", buf, 1 + _N.size)
        return np.full(n, val, dtype=np.float64)
    if wire != WireType.XOR_DOUBLE:
        raise ValueError(f"not a double vector: wire type {wire}")
    (n,) = _N.unpack_from(buf, 1)
    if _native is not None:
        return _native.xor_unpack(buf, n, 1 + _N.size)
    residuals, _ = nibblepack.unpack(buf, n, 1 + _N.size)
    # invert the XOR-with-previous chain via cumulative xor
    bits = np.bitwise_xor.accumulate(residuals)
    return bits.view(np.float64)


def num_values(buf: bytes) -> int:
    wire = buf[0]
    if wire == WireType.DELTA2_DOUBLE:
        return deltadelta.num_values(buf[1:])
    return _N.unpack_from(buf, 1)[0]
