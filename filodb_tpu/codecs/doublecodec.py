"""Float64 vector codec.

Strategy mirrors the reference's DoubleVector optimizer (reference:
memory/src/main/scala/filodb.memory/format/vectors/DoubleVector.scala:14):

- all values integral and line-like  -> route through the DELTA2 long codec
  (``DELTA2_DOUBLE``), the common case for counters ingested as doubles;
- constant vectors -> ``CONST_DOUBLE``;
- otherwise -> previous-value XOR predictor, residuals stored as the
  SMALLER of two forms: bit-level Gorilla windows (``GORILLA_DOUBLE``)
  or NibblePack (``XOR_DOUBLE``; doc/compression.md "Floating Point
  Compression") — unless neither saves >=10% over raw, in which case
  ``RAW_DOUBLE`` wins: incompressible (IID-noise) data decodes with one
  memcpy instead of a bit-stream walk (the batch downsampler's read
  side is decode-bound on such data).

``GORILLA_DOUBLE`` keeps Gorilla's information layout — 1 bit for a
repeat, leading-zero count + significant length + significant bits
otherwise (the reference's time-series paper lineage) — but in a
STRUCTURE-OF-ARRAYS stream instead of one sequential bit tape:

    [n u32][nnz u32][zero-bitmap ceil(n/8)]
    [12-bit headers: clz(6) | siglen-1(6), one per nonzero]
    [concatenated significant bits, LSB-first]

Splitting control/header/payload planes makes BOTH encode and decode
fully vectorizable (numpy today, a trivial TPU/pallas port tomorrow) —
the classic Gorilla tape forces bit-serial decode.  On realistic gauge
streams (repeats + slowly-moving mantissas) this lands the same >=2x
the sequential format gets; on adversarial IID noise the NibblePack
fallback wins and is chosen by size.

NaN is used by ingestion as the "no data" sentinel, exactly like the
reference's Prometheus schemas; NaNs survive round-trip bit-exactly
through the XOR paths.
"""

from __future__ import annotations

import struct

import numpy as np

from filodb_tpu.codecs import deltadelta, nibblepack
from filodb_tpu.codecs.wire import WireType

_N = struct.Struct("<I")

_native = None  # set by filodb_tpu.native when the shared lib is importable

_U64_1 = np.uint64(1)


def encode_batch(arrays) -> list[bytes]:
    """Encode many float64 vectors with the full selector; ONE native
    call when available (the flush/downsample hot loop)."""
    if _native is not None and hasattr(_native, "dbl_encode_batch"):
        return _native.dbl_encode_batch(arrays)
    return [encode(np.asarray(a, dtype=np.float64)) for a in arrays]


def encode_batch_2d(arr2d: np.ndarray) -> list[bytes]:
    """Encode every row of a [nvec, n] float64 matrix (the columnar
    downsample write path): the contiguous layout skips the per-vector
    gather of :func:`encode_batch`."""
    if _native is not None and hasattr(_native, "dbl_encode_batch_2d"):
        return _native.dbl_encode_batch_2d(arr2d)
    return [encode(row) for row in np.asarray(arr2d, dtype=np.float64)]


def _bit_length64(x: np.ndarray) -> np.ndarray:
    """Vectorized exact bit length of u64 (0 -> 0): frexp on the 32-bit
    halves (each exact in f64) — one pass instead of a shift cascade."""
    hi = (x >> np.uint64(32)).astype(np.float64)
    lo = (x & np.uint64(0xFFFFFFFF)).astype(np.float64)
    _, ehi = np.frexp(hi)
    _, elo = np.frexp(lo)
    return np.where(hi > 0, ehi + 32, elo).astype(np.uint64)


def _gorilla_plan(residuals: np.ndarray):
    """Cheap per-value window analysis: (nz, clz, ctz, lens, nbytes).
    The encoded size is closed-form from the windows alone, so the
    encode selector can pick a winner WITHOUT materializing the (much
    more expensive) bitstream of the loser."""
    n = len(residuals)
    nz = residuals != 0
    nnz = int(nz.sum())
    if nnz == 0:
        nbytes = 2 * _N.size + (n + 7) // 8
        return nz, None, None, None, nbytes
    r = residuals[nz]
    bl = _bit_length64(r)
    clz = np.uint64(64) - bl
    ctz = _bit_length64(r & (~r + _U64_1)) - _U64_1  # lowest set bit idx
    lens = bl - ctz                              # significant bits, >= 1
    total = int(lens.astype(np.int64).sum())
    nbytes = (2 * _N.size + (n + 7) // 8 + (nnz * 12 + 7) // 8
              + (total + 7) // 8)
    return nz, clz, ctz, lens, nbytes


def _gorilla_pack(residuals: np.ndarray, plan=None) -> bytes:
    n = len(residuals)
    nz, clz, ctz, lens, _ = plan if plan is not None \
        else _gorilla_plan(residuals)
    bitmap = np.packbits(nz, bitorder="little").tobytes()
    if clz is None:
        return _N.pack(n) + _N.pack(0) + bitmap
    nnz = len(clz)
    sig = residuals[nz] >> ctz
    # 12-bit headers: clz(6) | len-1(6), fixed width -> one packbits
    hdr = (clz << np.uint64(6)) | (lens - _U64_1)
    hdr_bits = ((hdr[:, None] >> np.arange(12, dtype=np.uint64)) &
                _U64_1).astype(np.uint8)
    headers = np.packbits(hdr_bits.ravel(), bitorder="little").tobytes()
    # significant-bit stream, LSB-first within each value
    lens_i = lens.astype(np.int64)
    offs = np.zeros(nnz, np.int64)
    np.cumsum(lens_i[:-1], out=offs[1:] if nnz > 1 else offs[:0])
    total = int(lens_i.sum())
    pos = np.arange(total, dtype=np.int64) - np.repeat(offs, lens_i)
    bits = ((np.repeat(sig, lens_i) >> pos.astype(np.uint64)) &
            _U64_1).astype(np.uint8)
    payload = np.packbits(bits, bitorder="little").tobytes()
    return _N.pack(n) + _N.pack(nnz) + bitmap + headers + payload


def _gorilla_unpack(buf, offset: int) -> np.ndarray:
    (n,) = _N.unpack_from(buf, offset)
    (nnz,) = _N.unpack_from(buf, offset + _N.size)
    o = offset + 2 * _N.size
    bm_bytes = (n + 7) // 8
    nz = np.unpackbits(np.frombuffer(buf, np.uint8, bm_bytes, o),
                       bitorder="little")[:n].astype(bool)
    o += bm_bytes
    residuals = np.zeros(n, np.uint64)
    if nnz:
        hdr_bytes = (nnz * 12 + 7) // 8
        hbits = np.unpackbits(
            np.frombuffer(buf, np.uint8, hdr_bytes, o),
            bitorder="little")[:nnz * 12].astype(np.uint64)
        hdr = (hbits.reshape(nnz, 12)
               << np.arange(12, dtype=np.uint64)).sum(axis=1)
        o += hdr_bytes
        clz = hdr >> np.uint64(6)
        lens = (hdr & np.uint64(63)) + _U64_1
        ctz = np.uint64(64) - clz - lens
        lens_i = lens.astype(np.int64)
        total = int(lens_i.sum())
        sig_bytes = (total + 7) // 8
        sbits = np.unpackbits(
            np.frombuffer(buf, np.uint8, sig_bytes, o),
            bitorder="little")[:total].astype(np.uint64)
        offs = np.zeros(nnz, np.int64)
        np.cumsum(lens_i[:-1], out=offs[1:] if nnz > 1 else offs[:0])
        pos = (np.arange(total, dtype=np.int64)
               - np.repeat(offs, lens_i)).astype(np.uint64)
        weighted = sbits << pos
        sig = np.add.reduceat(weighted, offs)
        residuals[nz] = sig << ctz
    bits = np.bitwise_xor.accumulate(residuals)
    return bits.view(np.float64)


def encode(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.float64)
    n = len(v)
    if (n and np.isfinite(v).all() and (np.abs(v) < 2**63).all()
            and not (np.signbit(v) & (v == 0)).any()):  # -0.0 must keep its sign bit
        as_int = v.astype(np.int64)
        if (as_int.astype(np.float64) == v).all():
            inner = deltadelta.encode(as_int)
            return bytes([WireType.DELTA2_DOUBLE]) + inner
    if n and np.all(v[0] == v) and not np.isnan(v[0]):
        return bytes([WireType.CONST_DOUBLE]) + _N.pack(n) + struct.pack("<d", v[0])
    bits = v.view(np.uint64)
    prev = np.concatenate([[np.uint64(0)], bits[:-1]])
    residuals = bits ^ prev
    packed = nibblepack.pack(residuals)
    plan = _gorilla_plan(residuals)
    best = min(plan[-1], len(packed) + _N.size)
    # compression must pay for itself: on incompressible data (IID
    # noise) the bit-packed forms land within a few % of raw while
    # decoding orders of magnitude slower (bit streams vs one memcpy) —
    # take RAW unless the winner saves >=10%.  Integer rule, mirrored
    # exactly by the native encoder (codecs.cpp dbl_encode_one) so the
    # byte-pairing tests hold.
    raw_bytes = _N.size + 8 * n
    if best * 10 > raw_bytes * 9:
        return bytes([WireType.RAW_DOUBLE]) + _N.pack(n) + v.tobytes()
    if plan[-1] <= len(packed) + _N.size:
        return bytes([WireType.GORILLA_DOUBLE]) \
            + _gorilla_pack(residuals, plan)
    return bytes([WireType.XOR_DOUBLE]) + _N.pack(n) + packed


def decode(buf: bytes) -> np.ndarray:
    wire = buf[0]
    if wire == WireType.DELTA2_DOUBLE:
        return deltadelta.decode(buf[1:]).astype(np.float64)
    if wire == WireType.CONST_DOUBLE:
        (n,) = _N.unpack_from(buf, 1)
        (val,) = struct.unpack_from("<d", buf, 1 + _N.size)
        return np.full(n, val, dtype=np.float64)
    if wire == WireType.GORILLA_DOUBLE:
        return _gorilla_unpack(buf, 1)
    if wire == WireType.RAW_DOUBLE:
        (n,) = _N.unpack_from(buf, 1)
        return np.frombuffer(buf, np.float64, n, 1 + _N.size).copy()
    if wire != WireType.XOR_DOUBLE:
        raise ValueError(f"not a double vector: wire type {wire}")
    (n,) = _N.unpack_from(buf, 1)
    if _native is not None:
        return _native.xor_unpack(buf, n, 1 + _N.size)
    residuals, _ = nibblepack.unpack(buf, n, 1 + _N.size)
    # invert the XOR-with-previous chain via cumulative xor
    bits = np.bitwise_xor.accumulate(residuals)
    return bits.view(np.float64)


def num_values(buf: bytes) -> int:
    wire = buf[0]
    if wire == WireType.DELTA2_DOUBLE:
        return deltadelta.num_values(buf[1:])
    return _N.unpack_from(buf, 1)[0]
