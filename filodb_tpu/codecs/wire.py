"""Wire-format type codes for encoded vectors.

Mirrors the *role* of the reference's vector type/subtype registry
(reference: memory/src/main/scala/filodb.memory/format/WireFormat.scala:7-37),
which tags every frozen BinaryVector with a (major, subtype) pair so readers
can be chosen at decode time.  Our encoded chunks carry a 1-byte ``WireType``
header followed by codec-specific payload.
"""

from __future__ import annotations

import enum


class WireType(enum.IntEnum):
    """Codec identifier stored as the first byte of every encoded vector."""

    # Timestamps / longs
    DELTA2 = 1          # delta-delta sloped-line model + nibble-packed residuals
    CONST_LONG = 2      # constant value or perfect line (base + slope only)
    RAW_LONG = 3        # uncompressed little-endian int64
    # Doubles
    DELTA2_DOUBLE = 16  # integral doubles encoded through the long path
    XOR_DOUBLE = 17     # previous-value XOR predictor + nibble-packed residuals
    RAW_DOUBLE = 18     # uncompressed little-endian float64
    CONST_DOUBLE = 19
    GORILLA_DOUBLE = 20  # XOR predictor + bit-level Gorilla windows (SoA)
    # Histograms
    HIST_2D_DELTA = 32  # per-row delta vs previous row, nibble-packed sections
    HIST_BLOB = 33      # single-sample BinaryHistogram blob (ingest wire form)
    # Strings / tags
    UTF8_DENSE = 48     # offsets + concatenated UTF-8 payload
    DICT_UTF8 = 49      # dictionary-encoded UTF-8
    # Ints
    INT_NBIT = 64       # nbits-packed small ints


HEADER_SIZE = 1
