"""Fleet batching tier (ISSUE 20, ROADMAP item 2).

Concurrent shape-compatible queries — the thousands of dashboard
panels refreshing against the same hot dataset — rendezvous at the
device-dispatch boundary and execute as ONE vmapped device program
over the shared resident planes (the DrJAX vmap-over-clients idiom,
arXiv:2403.07128), instead of paying N serving launches for N queries
whose plans differ only in their start step.

``QueryBatcher`` is the rendezvous: the device store offers every
eligible dispatch (batch key + the member's ``(row0, steps0)`` stack
axis + a batched launch closure); the batcher groups co-arrivals
inside a short bounded window, a leader launches the stacked program,
and every member gets its own slice of the single readback.  Any
failure demotes the whole group through a bit-identical per-query
fallback (breaker + ``filodb_batch_fallbacks_total{reason=}``).

See doc/batching.md for the batch-key contract, knobs, and the
fallback ladder.
"""

from .batcher import QueryBatcher, batching_broken, reset_batch_breaker

__all__ = ["QueryBatcher", "batching_broken", "reset_batch_breaker"]
