"""Query batcher: co-arrival rendezvous at the device-dispatch boundary.

The serving path offers every eligible device dispatch to the shard's
``QueryBatcher`` (``TimeSeriesShard.query_batcher``, attached by the
standalone wiring).  Queries whose fused plans share a batch key —
same resident planes, same ``GridQuery`` signature, same grid shape,
differing only in the traced ``(row0, steps0)`` start — are stacked
and launched as ONE vmapped device program; each member receives its
own slice of the single readback, bit-equal to what its solo launch
would have produced.

Gating is adaptive so a lone query never waits:

* an OPEN group for the key exists  -> join it (deadline permitting);
* the key is HOT (a real group formed recently) or another dispatch
  for the key is in flight right now -> lead a new group and hold the
  co-arrival window;
* otherwise -> pure passthrough: the solo closure runs immediately,
  tracked only so a concurrent twin can detect the overlap and
  bootstrap the first group.

Every member still holds its own admission permit and deadline: a
query whose remaining budget cannot afford the window joins no batch,
and the leader re-checks each member's budget at stack time — expired
or permit-released members are dropped from the stack and fall back
to the ordinary per-query chain (where the deadline tripwires fire
exactly as today).  Any batched-path error trips a process breaker
(PR 22 ladder discipline): the group demotes to per-query launches
and the batcher becomes a passthrough until ``reset_batch_breaker``.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from filodb_tpu.utils.devicewatch import FLIGHT
from filodb_tpu.utils.observability import batch_metrics
from filodb_tpu.workload import deadline as wdl

_BATCH_BROKEN = False


def batching_broken() -> bool:
    return _BATCH_BROKEN


def reset_batch_breaker() -> None:
    """Close the batched-path breaker (ops verb / tests)."""
    global _BATCH_BROKEN
    _BATCH_BROKEN = False


def _pad_pow2(n: int, cap: int) -> int:
    """Smallest power of two >= n (capped): bounds the compile count of
    the vmapped programs to log2(max_batch)+1 leading-axis shapes."""
    p = 1
    while p < n and p < cap:
        p *= 2
    return min(p, cap)


class _Group:
    """One forming batch: members stack under the batcher lock; the
    leader launches once the group is full or the window expires."""

    __slots__ = ("key", "members", "open", "full", "done", "results")

    def __init__(self, key):
        self.key = key
        self.members: list = []
        self.open = True
        self.full = threading.Event()
        self.done = threading.Event()
        # list parallel to members (None = fall back solo), or None
        # when the whole group demoted
        self.results = None


class _Member:
    __slots__ = ("row0", "steps0", "qctx")

    def __init__(self, row0, steps0, qctx):
        self.row0, self.steps0, self.qctx = row0, steps0, qctx


class QueryBatcher:
    """Per-dataset rendezvous for vmapped execution of concurrent
    shape-compatible queries (ISSUE 20 tentpole)."""

    def __init__(self, *, enabled: bool = True, window_ms: float = 3.0,
                 max_batch: int = 8, hot_ttl_s: float = 10.0,
                 slack_ms: float = 25.0, dataset: str = "",
                 ledger=None):
        self.enabled = bool(enabled)
        self.window_ms = float(window_ms)
        self.max_batch = int(max_batch)
        self.hot_ttl_s = float(hot_ttl_s)
        # extra deadline budget a joiner must hold beyond the window
        # (covers the stacked launch + readback)
        self.slack_ms = float(slack_ms)
        self.dataset = dataset
        # WorkloadLedger for realized group sizes, or a zero-arg
        # callable resolving to one (the standalone wiring installs the
        # configured ledger AFTER datasets bind)
        self.ledger = ledger
        self._lock = threading.Lock()
        self._groups: dict = {}       # key -> open _Group
        self._inflight: dict = {}     # key -> concurrent solo dispatches
        self._hot: dict = {}          # key -> monotonic expiry
        self._m = batch_metrics()
        self._peak = 0

    # ------------------------------------------------------------ config

    def configure(self, *, enabled=None, window_ms=None, max_batch=None,
                  hot_ttl_s=None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if window_ms is not None:
            self.window_ms = float(window_ms)
        if max_batch is not None:
            self.max_batch = max(1, int(max_batch))
        if hot_ttl_s is not None:
            self.hot_ttl_s = float(hot_ttl_s)

    def snapshot(self) -> dict:
        return {"enabled": self.enabled, "window_ms": self.window_ms,
                "max_batch": self.max_batch,
                "hot_ttl_s": self.hot_ttl_s,
                "breaker_open": _BATCH_BROKEN,
                "realized_peak": self._peak}

    # ---------------------------------------------------------- dispatch

    def dispatch(self, key, row0, steps0, qctx, batch_launch, solo):
        """Offer one device dispatch to the batching tier.

        Returns the member's result (its slice of the stacked launch,
        or the solo result when the batcher ran the passthrough), or
        None when the caller must run its own solo fallback — the
        existing per-query chain, bit-identical to a batcher-less
        serve.  ``batch_launch(row0s, steps0s)`` must return the
        stacked readback with the member axis leading."""
        if not self.enabled:
            return None
        if _BATCH_BROKEN:
            self._m["fallbacks"].inc(dataset=self.dataset,
                                     reason="breaker")
            return None
        window_ms = self.window_ms
        if qctx is not None and getattr(qctx, "deadline_ms", 0):
            if wdl.remaining_ms(qctx) < window_ms + self.slack_ms:
                # remaining budget can't afford the co-arrival window:
                # this query joins no batch (ISSUE 20 contract)
                self._m["fallbacks"].inc(dataset=self.dataset,
                                         reason="deadline")
                return None
        now = time.monotonic()
        lead = False
        with self._lock:
            g = self._groups.get(key)
            if g is not None and g.open:
                my = len(g.members)
                g.members.append(_Member(row0, steps0, qctx))
                if len(g.members) >= self.max_batch:
                    g.open = False
                    self._groups.pop(key, None)
                    g.full.set()
            elif (self._hot.get(key, 0.0) > now
                  or self._inflight.get(key, 0) > 0):
                g = _Group(key)
                g.members.append(_Member(row0, steps0, qctx))
                self._groups[key] = g
                my, lead = 0, True
            else:
                # cold, no concurrent twin: pure passthrough — but
                # tracked, so an overlapping arrival bootstraps the
                # first group for this key
                self._inflight[key] = self._inflight.get(key, 0) + 1
                g = None
        if g is None:
            try:
                return solo()
            finally:
                with self._lock:
                    n = self._inflight.get(key, 1) - 1
                    if n > 0:
                        self._inflight[key] = n
                    else:
                        self._inflight.pop(key, None)
        if lead:
            self._lead(g, window_ms, batch_launch)
        elif not g.done.wait(timeout=window_ms / 1000.0 + 60.0):
            self._m["fallbacks"].inc(dataset=self.dataset,
                                     reason="timeout")
            return None
        res = g.results[my] if g.results is not None else None
        return res

    # ------------------------------------------------------------ leader

    def _lead(self, g, window_ms, batch_launch) -> None:
        end = time.monotonic() + window_ms / 1000.0
        while not g.full.is_set():
            left = end - time.monotonic()
            if left <= 0:
                break
            g.full.wait(left)
        with self._lock:
            g.open = False
            if self._groups.get(g.key) is g:
                self._groups.pop(g.key, None)
        try:
            self._launch_group(g, batch_launch)
        except Exception as e:     # demote the whole group
            global _BATCH_BROKEN
            _BATCH_BROKEN = True
            g.results = None
            FLIGHT.record("breaker.trip", breaker="query_batch",
                          error=repr(e)[:200])
            self._m["fallbacks"].inc(len(g.members),
                                     dataset=self.dataset,
                                     reason="error")
            import logging
            logging.getLogger(__name__).exception(
                "batched query launch failed; demoting the group to "
                "per-query launches and opening the batch breaker")
        finally:
            g.done.set()

    def _launch_group(self, g, batch_launch) -> None:
        """Stack the group's live members and launch once.

        Admission/deadline discipline (batch-admission-discipline
        lint): every stacked member must still hold its admission
        permit and have deadline budget left — members whose permit
        was released or whose ``deadline_ms`` budget expired while the
        window was open are dropped from the stack and demote to the
        per-query chain, where the ordinary tripwires raise."""
        members = g.members
        if len(members) < 2:
            # window expired with no co-arrival: no batch win — the
            # lone member (the leader) runs its unchanged solo chain
            g.results = None
            self._m["fallbacks"].inc(dataset=self.dataset,
                                     reason="solo-window")
            return
        live = []
        for i, m in enumerate(members):
            qc = m.qctx
            permit = getattr(qc, "admission_permit", None)
            if permit is not None and getattr(permit, "released", False):
                continue           # admission window closed mid-batch
            if qc is not None and getattr(qc, "deadline_ms", 0) \
                    and wdl.remaining_ms(qc) <= 0:
                continue           # budget died while the window held
            live.append(i)
        dropped = len(members) - len(live)
        if dropped:
            self._m["fallbacks"].inc(dropped, dataset=self.dataset,
                                     reason="member-expired")
        if len(live) < 2:
            g.results = None
            if live:
                self._m["fallbacks"].inc(dataset=self.dataset,
                                         reason="solo-window")
            return
        b = len(live)
        padded = _pad_pow2(b, self.max_batch)
        idx = live + [live[0]] * (padded - b)
        row0s = np.asarray([members[i].row0 for i in idx])
        steps0s = np.asarray([members[i].steps0 for i in idx])
        out = batch_launch(row0s, steps0s)
        results = [None] * len(members)
        for j, i in enumerate(live):
            results[i] = out[j]
        g.results = results
        self._note_realized(g.key, members, live)

    def _note_realized(self, key, members, live) -> None:
        size = len(live)
        self._m["groups"].inc(dataset=self.dataset)
        self._m["members"].inc(size, dataset=self.dataset)
        if size > self._peak:
            self._peak = size
            self._m["peak"].set(size, dataset=self.dataset)
        now = time.monotonic()
        with self._lock:
            self._hot[key] = now + self.hot_ttl_s
            if len(self._hot) > 256:
                self._hot = {k: t for k, t in self._hot.items()
                             if t > now}
        ledger = self.ledger() if callable(self.ledger) else self.ledger
        if ledger is not None:
            seen = set()
            for i in live:
                bk = getattr(members[i].qctx, "batch_key", "")
                if bk and bk not in seen:
                    seen.add(bk)
                    ledger.note_batch(bk, size)
