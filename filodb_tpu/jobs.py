"""Offline maintenance jobs: chunk repair, cardinality busting, index
migration.

Capability match for the reference's spark-jobs suite (reference:
spark-jobs/src/main/scala/filodb/repair/ChunkCopier.scala:22 —
cross-cluster chunk copy by ingestion-time range; cardbuster/
PerShardCardinalityBuster.scala:20 — delete partkeys matching filters;
index/DSIndexJob.scala:17 — migrate partkey index entries from the raw
dataset to downsample datasets).  Spark's executor parallelism maps to
per-(shard × time-split) work items driven by plain loops or a thread
pool — each item is independent.
"""

from __future__ import annotations

from typing import Optional, Sequence

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.record import parse_partkey
from filodb_tpu.store.columnstore import ColumnStore, PartKeyRecord


class ChunkCopier:
    """Copies chunks (and partkeys) between column stores for a dataset +
    ingestion-time range — disaster repair between clusters (reference:
    ChunkCopier.run)."""

    def __init__(self, source: ColumnStore, target: ColumnStore,
                 source_dataset: str, target_dataset: Optional[str] = None,
                 batch_size: int = 1000):
        self.source = source
        self.target = target
        self.source_dataset = source_dataset
        self.target_dataset = target_dataset or source_dataset
        self.batch_size = batch_size

    def copy_shard(self, shard: int, ingestion_start: int,
                   ingestion_end: int) -> int:
        """One (shard × time-split) work item; returns chunksets copied.
        Per-chunk ingestion times are preserved so incremental/overlapping
        repair runs and batch-downsample scans on the target see the same
        timeline as the source."""
        copied = 0
        by_itime: dict[int, list] = {}
        copied_pks: set[bytes] = set()

        def flush_groups():
            nonlocal copied
            for itime, group in by_itime.items():
                self.target.write_chunks(self.target_dataset, shard, group,
                                         ingestion_time=itime)
                copied += len(group)
            by_itime.clear()

        pending = 0
        for itime, cs in self.source.chunksets_with_ingestion_time(
                self.source_dataset, shard, ingestion_start, ingestion_end):
            by_itime.setdefault(itime, []).append(cs)
            copied_pks.add(cs.partkey)
            pending += 1
            if pending >= self.batch_size:
                flush_groups()
                pending = 0
        flush_groups()
        # bring the partkey records along so the target can recover its index
        recs = [r for r in self.source.scan_part_keys(self.source_dataset,
                                                      shard)
                if r.partkey in copied_pks]
        if recs:
            self.target.write_part_keys(self.target_dataset, shard, recs)
        return copied

    def run(self, shards: Sequence[int], ingestion_start: int,
            ingestion_end: int) -> dict[int, int]:
        return {s: self.copy_shard(s, ingestion_start, ingestion_end)
                for s in shards}


class PerShardCardinalityBuster:
    """Deletes partkeys (and their chunks) whose tags match the given
    filters — the escape hatch for cardinality explosions (reference:
    PerShardCardinalityBuster.scala:20)."""

    def __init__(self, store: ColumnStore, dataset: str):
        self.store = store
        self.dataset = dataset

    def matching_partkeys(self, shard: int,
                          filters: Sequence[ColumnFilter]) -> list[bytes]:
        out = []
        for rec in self.store.scan_part_keys(self.dataset, shard):
            tags = parse_partkey(rec.partkey)
            if all(f.matches(tags) for f in filters):
                out.append(rec.partkey)
        return out

    def bust_shard(self, shard: int, filters: Sequence[ColumnFilter],
                   dry_run: bool = True) -> int:
        """Returns partkeys matched (deleted unless dry_run — the
        reference defaults to a dry run for the same reason)."""
        pks = self.matching_partkeys(shard, filters)
        if pks and not dry_run:
            self.store.delete_part_keys(self.dataset, shard, pks)
        return len(pks)

    def run(self, shards: Sequence[int], filters: Sequence[ColumnFilter],
            dry_run: bool = True) -> dict[int, int]:
        return {s: self.bust_shard(s, filters, dry_run) for s in shards}


class DSIndexJob:
    """Migrates partkey records from the raw dataset to its downsample
    datasets so downsample indexes can bootstrap (reference:
    DSIndexJob.updateDSPartKeyIndex)."""

    def __init__(self, store: ColumnStore, raw_dataset: str,
                 resolutions_ms: Sequence[int]):
        from filodb_tpu.downsample.dsstore import ds_dataset_name
        self.store = store
        self.raw_dataset = raw_dataset
        self.ds_names = [ds_dataset_name(raw_dataset, r)
                         for r in resolutions_ms]

    def migrate_shard(self, shard: int) -> int:
        recs = list(self.store.scan_part_keys(self.raw_dataset, shard))
        if not recs:
            return 0
        for name in self.ds_names:
            self.store.write_part_keys(name, shard, recs)
        return len(recs)

    def run(self, shards: Sequence[int]) -> dict[int, int]:
        return {s: self.migrate_shard(s) for s in shards}
