"""Aligned-grid leaf kernels: the memory-bound serving fast path.

The device chunk store lays frozen chunks out as a **time-major bucket
grid**: ``ts/vals [B, S]`` where column *s* is a series (lanes) and row
*c* is a time bucket (sublanes), with the layout invariant that the
sample in row ``c`` has ``ts in (t0 + (c-1)*gstep, t0 + c*gstep]`` and
missing buckets hold NaN.  PromQL range queries evaluate on a regular
step grid, so when ``window % gstep == 0`` and the query steps land on
bucket edges, every window covers exactly ``K = window//gstep`` full
buckets — **static sublane slices**, no searchsorted, no gathers.

This replaces the reference's per-window row iteration
(reference: query/exec/rangefn/RangeFunction.scala:102-161 addChunks +
binarySearch; AggrOverRangeVectors.scala:161-277 fastReduce) with one
fused pass: counter correction (prefix scan) -> per-window first/last
finite sample extraction (K select passes) -> Prometheus extrapolated
rate (RateFunctions.scala:37-80) -> grouped sum/count reduction, all in
VMEM.  Measured 1.8e10 samples/s on one v5e chip for
``sum by (g)(rate(m[5m]))`` over 1M series x 60 samples — ~25x the
unaligned gather-free path.

Two implementations with identical semantics:

- :func:`rate_grid` / :func:`rate_grid_grouped` — Pallas TPU kernels.
- :func:`rate_grid_ref` — pure-XLA reference (runs everywhere; used on
  CPU and as the numerical oracle in tests).

Layout contract (enforced by the caller / device store):
- ``ts`` int32 milliseconds relative to an epoch the caller also
  subtracts from the query steps (absolute ms overflow int32).
- query step == ``gstep`` (the dashboard case; others fall back to
  :mod:`filodb_tpu.ops.windows`), ``window == K * gstep``.
- the caller slices the stored grid so that window ``t`` (ending at
  ``steps0 + t*gstep``) covers input rows ``[t, t+K-1]`` — i.e. row 0
  is the first bucket of the first window.  Mosaic requires dynamic
  sublane offsets to be 8-aligned, so the per-query row offset is
  applied host-side (an XLA ``dynamic_slice``), keeping ONE compiled
  kernel per (T, K) signature; ``steps0`` stays a traced SMEM scalar.
- counter correction runs from input row 0, i.e. from the start of the
  scanned range — same scope as the general path, which corrects from
  the first scanned row (filodb_tpu/ops/windows.py counter_correct).
- grouped variant: series pre-sorted by group, each group padded to
  ``group_lanes`` columns (pad columns hold NaN vals), and the number
  of groups padded to a multiple of 8.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from filodb_tpu.utils import devicewatch

_IBIG = 2**30


def on_tpu_backend() -> bool:
    """One shared predicate for every formulation switch: the one-hot /
    Pallas paths exist for TPU-class backends; anything else takes the
    portable gathers."""
    return jax.default_backend() in ("tpu", "axon")


def _win_slicer(q: "GridQuery", ns: int):
    """Window-indexed slice: row d of window t is input row t*stride+d,
    so slicing at offset d with row-stride q.stride yields the [T, ns]
    tile of every window's d-th row — static slices, no gathers.

    Only the portable reference path takes stride here: Mosaic cannot
    lower a strided sublane slice (vector.extract_strided_slice requires
    stride 1), so the Pallas wrappers run the stride-1 fine grid and
    subsample OUTSIDE the kernel (see _fine_query)."""
    T = q.nsteps
    if q.stride == 1:
        return lambda x, d: jax.lax.slice(x, (d, 0), (d + T, ns))
    return lambda x, d: jax.lax.slice(
        x, (d, 0), (d + (T - 1) * q.stride + 1, ns), (q.stride, 1))


def _fine_query(q: "GridQuery") -> "GridQuery":
    """The stride-1 query computing every bucket-edge window of q's
    range: q's window t is fine window t*stride."""
    return q._replace(nsteps=(q.nsteps - 1) * q.stride + 1, stride=1)


def _rows_needed(q: "GridQuery") -> int:
    return (q.nsteps - 1) * q.stride + q.kbuckets


class GridQuery(NamedTuple):
    """Static kernel configuration for one (shape, query-grid) signature.

    ``op`` selects the fused window function:
      "rate" / "increase"  — counter correction + Prometheus extrapolation
      "sum" / "count" / "avg" / "min" / "max"
                           — the *_over_time family (no correction)
      "last"               — last_over_time / the instant-selector
                             staleness lookback
    ``is_rate`` is kept for backward compatibility with callers that
    predate ``op``; it is honored only when op is "rate"/"increase".

    ``dense`` asserts the **dense-lane contract**: over the used rows
    ``[0, (nsteps-1)*stride + kbuckets)`` every lane is either finite in
    ALL rows or finite in NONE (rows beyond the used range are
    unconstrained).  Regular scrapes with no missed samples — the
    dominant production shape and the QueryInMemoryBenchmark shape —
    satisfy it.  The kernel then skips the NaN-hole forward-fill and
    collapses the K-pass window loops to two static slices (first/last
    sample of each window are rows ``t`` and ``t+K-1``), roughly
    halving VPU work.  The caller must PROVE the contract (the device
    store tracks per-block, per-lane fill ranges); setting it on
    non-conforming data yields wrong results, not an error.
    """

    nsteps: int       # T output steps
    kbuckets: int     # K = window // gstep buckets per window
    gstep_ms: int     # bucket width (== query step when stride == 1)
    is_rate: bool = True   # rate() vs increase() (when op is rate-like)
    op: str = "rate"
    dense: bool = False
    # scalar function arguments (predict_linear's horizon seconds;
    # holt_winters' smoothing factors); static, so each distinct value
    # compiles its own kernel — dashboards use a handful of fixed values
    farg: float = 0.0
    farg2: float = 0.0
    # query step = stride * gstep: window t covers input rows
    # [t*stride, t*stride + K - 1].  Dashboards commonly query with a
    # coarser step than the scrape cadence (step 5m over 1m data);
    # strided static slices keep those on the fast path without
    # computing the skipped windows.
    stride: int = 1


def _correct_and_mask(ts, vals, roll):
    """Counter correction (prefix formulation of the reference's
    CorrectionMeta threading) + finite mask, on a [B, L] tile.

    A reset must be detected against the previous *finite* sample — a
    missed scrape leaves a NaN bucket, and comparing against NaN would
    silently skip the correction (the dense general path has no holes).
    The previous finite value is a log-step forward-fill scan."""
    nb = ts.shape[0]
    fin = jnp.isfinite(vals)
    row = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 0)
    # forward fill: ffill[r] = last finite value at row <= r.  The mask
    # scans as int32 — Mosaic's dynamic_rotate has no i1 lowering
    # ("Rotate with non-32-bit data"), so never roll a bool tile.
    fv, fm = vals, fin.astype(jnp.int32)
    sh = 1
    while sh < nb:
        shifted_v, shifted_m = roll(fv, sh), roll(fm, sh)
        in_range = row >= sh
        fv = jnp.where(fm > 0, fv, jnp.where(in_range, shifted_v, fv))
        fm = fm | jnp.where(in_range, shifted_m, 0)
        sh *= 2
    prev = roll(fv, 1)                         # last finite at row <= r-1
    return fin, _apply_reset_correction(vals, prev, row, roll)


def _apply_reset_correction(vals, prev, row, roll):
    """Given each row's previous sample, add the running sum of counter
    drops (prefix formulation of the reference's CorrectionMeta
    threading)."""
    nb = vals.shape[0]
    prev = jnp.where(row == 0, vals, prev)
    drop = jnp.where(vals < prev, prev, 0.0)   # NaN compares are False
    acc = drop
    sh = 1
    while sh < nb:
        acc = jnp.where(row >= sh, acc + roll(acc, sh), acc)
        sh *= 2
    return vals + acc


def _correct_dense(vals, roll):
    """Counter correction under the dense-lane contract: the previous
    sample IS the previous row (no holes), so the forward-fill scan
    disappears — one roll feeds the shared reset-correction scan."""
    row = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 0)
    return _apply_reset_correction(vals, roll(vals, 1), row, roll)


# above this row count the [B, B] triangular matmul's O(B^2) work and
# VMEM footprint overtake the O(B log B) roll-scan it replaces
_MXU_CORR_MAX_ROWS = 256


def _correct_dense_mxu(vals):
    """Dense counter correction with the prefix sum on the MXU: the
    cumulative drop is a lower-triangular ones-matmul over the per-row
    drops, replacing the log2(B) VPU roll-scan (measured +13% on the
    headline kernel; the [B, B] triangle is generated in-register).
    Row 0 has no previous sample — its (bogus, rolled-from-last-row)
    drop is excluded by zeroing the triangle's first column instead of
    masking the drop tile, saving an iota+where pass."""
    nb = vals.shape[0]
    prev = pltpu.roll(vals, 1, axis=0)
    drop = jnp.where(vals < prev, prev, 0.0)   # never NaN: prev or 0.0
    r1 = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
    r2 = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
    tri = ((r2 <= r1) & (r2 > 0)).astype(jnp.float32)
    acc = jax.lax.dot(tri, drop, precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
    return vals + acc


def _correct_dense_auto(vals, roll):
    """MXU prefix for short blocks; the roll-scan for tall ones (the
    K-free dense path admits up to MAX_GRID_ROWS=1024 rows, where the
    [B, B] matmul would do ~100x the arithmetic)."""
    if vals.shape[0] <= _MXU_CORR_MAX_ROWS:
        return _correct_dense_mxu(vals)
    return _correct_dense(vals, roll)


def _corr_v1_delta_banded(vals, q: GridQuery, roll):
    """Corrected window-start values and window deltas via ONE banded
    lower-triangular matmul — the MXU correction-prefix trick extended
    so ``vcorr`` is never materialized:

        v1[t]    = vals[t]       + sum_{0 < c <= t}       drop[c]
        delta[t] = vals[t+K-1] - vals[t] + sum_{t < c <= t+K-1} drop[c]

    Both prefix/band sums are rows of a [2T, B] 0/1 matrix applied to
    the [B, L] drop plane in one ``dot``, replacing the [B, B]
    triangular matmul + two sublane slices + subtract.  With 2T < B
    (the K-heavy dashboard shape: long windows, few steps) this is
    strictly less MXU work AND two fewer [B, L] VMEM passes; the
    caller keeps the [B, B] formulation otherwise."""
    nb = vals.shape[0]
    T, K = q.nsteps, q.kbuckets
    prev = roll(vals, 1)
    drop = jnp.where(vals < prev, prev, 0.0)   # row 0 excluded by c > 0
    r = jax.lax.broadcasted_iota(jnp.int32, (2 * T, nb), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (2 * T, nb), 1)
    t = jnp.where(r < T, r, r - T)
    lo = jnp.where(r < T, 0, t)                # c > lo
    hi = jnp.where(r < T, t, t + K - 1)        # c <= hi
    m = ((c > lo) & (c <= hi)).astype(jnp.float32)
    acc = jax.lax.dot(m, drop, precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
    sl = _win_slicer(q, vals.shape[1])
    v1 = sl(vals, 0) + acc[:T]
    delta = (sl(vals, K - 1) - sl(vals, 0)) + acc[T:]
    return v1, delta


# ops with a dense+uniform-phase kernel: the ts plane is never streamed;
# per-lane scrape phase (one row) reconstructs the extrapolation geometry
PHASE_OPS = frozenset(("rate", "increase", "delta"))


def _phase_block(phase_row, vals, q: GridQuery, roll, mxu: bool):
    """rate/increase/delta under dense + UNIFORM-PHASE: every live lane
    is scraped at a constant offset ``phase in (0, gstep]`` within its
    bucket, so ``t1 - window_start == phase`` and ``window_end - t2 ==
    gstep - phase`` are per-lane constants and ``sampled == (K-1)*gstep``
    exactly.  The reference extrapolation (RateFunctions.scala:37-80)
    then collapses:

    - ``avg_dur == gstep`` and both boundary gaps are < 1.1*gstep, so
      the threshold selects are always-true and vanish;
    - the counter zero-point clamp's divide cancels against the final
      ``delta *`` multiply: ``delta * (sampled*v1/delta * scale) ==
      sampled*v1*scale`` — the kernel is divide-free;
    - ``delta > 0`` is implied by ``v1 >= 0 & sampled*v1 < phase*delta``
      (phase > 0), dropping a compare.

    Liveness is row-0-derived (dense), so masks and the grouped count
    are [1, ns] rows, not [T, ns] tiles."""
    out, live_row = _phase_block_raw(phase_row, vals, q, roll, mxu)
    return jnp.where(live_row, out, jnp.nan)


def _phase_block_raw(phase_row, vals, q: GridQuery, roll, mxu: bool):
    """Unmasked phase-mode compute: returns ``(out [T, ns], live_row
    [1, ns])`` so grouped callers can mask-to-zero without a second
    [T, ns] pass.  ``out`` is finite wherever ``live_row`` holds (dense:
    K >= 2 samples, strictly increasing ts => sampled > 0)."""
    ns = vals.shape[1]
    dt = vals.dtype
    sl = _win_slicer(q, ns)
    K, g = q.kbuckets, q.gstep_ms
    live_row = jnp.isfinite(vals[0:1, :])
    if q.op == "delta":
        v1 = sl(vals, 0)
        delta = sl(vals, K - 1) - v1
    elif mxu and q.stride == 1 and vals.shape[0] <= _MXU_CORR_MAX_ROWS \
            and 2 * q.nsteps < vals.shape[0]:
        # K-heavy shape: the banded formulation does less MXU work than
        # the [B, B] prefix and skips materializing vcorr entirely
        v1, delta = _corr_v1_delta_banded(vals, q, roll)
    else:
        vcorr = _correct_dense_auto(vals, roll) if mxu \
            else _correct_dense(vals, roll)
        v1 = sl(vcorr, 0)
        delta = sl(vcorr, K - 1) - v1
    sampled = jnp.asarray((K - 1) * g * 1e-3, dt)
    if q.op == "delta":
        # no zero-clamp for gauges: extrap == sampled + gstep == K*gstep
        return delta * jnp.asarray(K / (K - 1), dt), live_row
    phase_s = phase_row.astype(dt) * jnp.asarray(1e-3, dt)       # [1, ns]
    g_s = jnp.asarray(g * 1e-3, dt)
    is_rate = q.op == "rate" and q.is_rate
    scale = jnp.asarray(1e3 / (K * g), dt) / sampled if is_rate \
        else jnp.asarray(1.0, dt) / sampled
    end_sc = (sampled + g_s - phase_s) * scale                   # [1, ns]
    sv1 = sampled * v1
    pd = phase_s * delta
    clamp = (sv1 < pd) & (v1 >= 0)
    start_num = jnp.where(clamp, sv1, pd)      # == delta * start_dur
    return delta * end_sc + start_num * scale, live_row


def phase_eligible(q: GridQuery) -> bool:
    """Can this query use the uniform-phase kernels (given a proven
    phase vector)?  K >= 2: the collapsed extrapolation divides by
    (K-1); the ts path's nf>=2 guard yields NaN for K=1, so routing
    K=1 there keeps semantics.  The device store must use THIS
    predicate when deciding to drop the ts plane from a plan — the
    kernel wrappers fall back to ts mode under the same condition.
    stride > 1 runs the stride-1 fine query inside the wrappers, so
    eligibility doesn't depend on it."""
    return q.dense and q.op in PHASE_OPS and q.kbuckets >= 2


def _phase_mode(q: GridQuery, phase) -> bool:
    return phase is not None and phase_eligible(q)


def _window_stats_dense(ts, vals, vcorr, q: GridQuery):
    """Window stats under the dense-lane contract: window ``t`` covers
    rows ``[t, t+K-1]`` and a live lane has a sample in every row, so
    first/last are static slices and the finite count is ``K`` exactly
    (0 for empty lanes)."""
    ns = vals.shape[1]
    dt = vcorr.dtype
    sl = _win_slicer(q, ns)
    live = jnp.isfinite(sl(vals, 0))
    nf = jnp.asarray(q.kbuckets, dt) * live.astype(dt)
    return nf, sl(ts, 0), sl(ts, q.kbuckets - 1), sl(vcorr, 0), \
        sl(vcorr, q.kbuckets - 1)


def _window_stats(ts, fin, vcorr, q: GridQuery):
    """First/last finite sample (ts and corrected value) + finite count
    per window, via K forward/backward select passes over static
    sublane slices: window t covers rows [t*stride, t*stride+K-1]."""
    ns = vcorr.shape[1]
    T = q.nsteps
    dt = vcorr.dtype
    sl = _win_slicer(q, ns)
    shape = (T, ns)
    nf = jnp.zeros(shape, dt)
    t2 = jnp.full(shape, _IBIG, ts.dtype)
    v2 = jnp.full(shape, jnp.nan, dt)
    for d in range(q.kbuckets):            # forward: last finite wins
        fd = sl(fin, d)
        nf = nf + fd.astype(dt)
        t2 = jnp.where(fd, sl(ts, d), t2)
        v2 = jnp.where(fd, sl(vcorr, d), v2)
    t1 = jnp.full(shape, _IBIG, ts.dtype)
    v1 = jnp.full(shape, jnp.nan, dt)
    for d in range(q.kbuckets - 1, -1, -1):  # reverse: first finite wins
        fd = sl(fin, d)
        t1 = jnp.where(fd, sl(ts, d), t1)
        v1 = jnp.where(fd, sl(vcorr, d), v1)
    return nf, t1, t2, v1, v2


def _extrapolate(nf, t1, t2, v1, v2, steps0, q: GridQuery):
    """Prometheus extrapolatedRate on [T, L] tiles (reference:
    RateFunctions.scala:37-80; same math as windows._extrapolated)."""
    ns = nf.shape[1]
    dt = v1.dtype
    window = q.kbuckets * q.gstep_ms
    tcol = jax.lax.broadcasted_iota(jnp.int32, (q.nsteps, ns), 0)
    hi = (steps0 + tcol * jnp.int32(q.gstep_ms * q.stride)).astype(dt)
    lo = hi - jnp.asarray(window, dt)
    t1f = t1.astype(dt)
    t2f = t2.astype(dt)
    dur_start = (t1f - lo) / 1000.0
    dur_end = (hi - t2f) / 1000.0
    sampled = (t2f - t1f) / 1000.0
    avg_dur = sampled / jnp.maximum(nf - 1.0, 1.0)
    delta = v2 - v1
    if q.op != "delta":    # counter zero-point clamp (rate/increase only)
        dur_zero = sampled * v1 / jnp.where(delta == 0, 1.0, delta)
        clamp = (delta > 0) & (v1 >= 0) & (dur_zero < dur_start)
        dur_start = jnp.where(clamp, dur_zero, dur_start)
    thresh = avg_dur * 1.1
    extrap = (sampled + jnp.where(dur_start < thresh, dur_start, avg_dur / 2.0)
              + jnp.where(dur_end < thresh, dur_end, avg_dur / 2.0))
    scaled = delta * extrap / jnp.where(sampled == 0, 1.0, sampled)
    # rate divides by window seconds; increase does not.  op is
    # authoritative ("increase" must never divide); is_rate only
    # disambiguates legacy callers that left op at its "rate" default.
    if q.op == "rate" and q.is_rate:
        scaled = scaled / (jnp.asarray(window, dt) / 1000.0)
    return jnp.where((nf >= 2) & (sampled > 0), scaled, jnp.nan)


def _instant_pair_block(ts, vals, q: GridQuery):
    """irate/idelta under the dense contract: the window's last two
    samples ARE its last two rows (reference: IRateFunction /
    windows._instant_pair).  K-free — two static slices.  The counter
    correction between ADJACENT samples collapses to the pair itself:
    vcorr2 - vcorr1 = v2 - v1 + (v1 if v2 < v1 else 0) = v2 on a reset,
    so no prefix scan is needed."""
    if not q.dense:
        raise ValueError(f"grid op {q.op} requires the dense contract")
    ns = vals.shape[1]
    dt = vals.dtype
    K = q.kbuckets
    sl = _win_slicer(q, ns)
    if K < 2:
        return jnp.full(((q.nsteps), ns), jnp.nan, dt)
    v2, v1 = sl(vals, K - 1), sl(vals, K - 2)
    t2, t1 = sl(ts, K - 1), sl(ts, K - 2)
    live = jnp.isfinite(v2)
    delta = v2 - v1
    if q.op == "irate":
        delta = jnp.where(v2 < v1, v2, delta)   # adjacent-pair reset
    dt_s = (t2 - t1).astype(dt) / 1000.0
    # the reference's shared instant-pair semantics drop a zero
    # sampledInterval for idelta and irate alike (ADVICE r2)
    if q.op == "idelta":
        return jnp.where(live & (dt_s > 0), delta, jnp.nan)
    return jnp.where(live & (dt_s > 0), delta / dt_s, jnp.nan)


def _agg_block_dense(ts, vals, q: GridQuery):
    """The *_over_time family under the dense-lane contract: live lanes
    have a sample in every row, so the per-slice finite masks vanish —
    NaN in empty lanes propagates through the accumulation and the
    single ``live`` mask finishes the job."""
    ns = vals.shape[1]
    dt = vals.dtype
    sl = _win_slicer(q, ns)
    if q.op == "last":
        return sl(vals, q.kbuckets - 1)
    live = jnp.isfinite(sl(vals, 0))
    if q.op == "count":
        return jnp.where(live, jnp.asarray(q.kbuckets, dt), jnp.nan)
    if q.op in ("changes", "resets"):
        # consecutive-row pairs fully inside the window (the reference's
        # pair semantics: windows.changes_over_time / resets_over_time)
        c = jnp.zeros(live.shape, dt)
        prev = sl(vals, 0)
        for d in range(1, q.kbuckets):
            cur = sl(vals, d)
            c = c + ((cur != prev) if q.op == "changes"
                     else (cur < prev)).astype(dt)
            prev = cur
        return jnp.where(live, c, jnp.nan)
    if q.op in ("sum", "avg"):
        s = sl(vals, 0)
        for d in range(1, q.kbuckets):
            s = s + sl(vals, d)
        if q.op == "avg":
            s = s / jnp.asarray(q.kbuckets, dt)
        return jnp.where(live, s, jnp.nan)
    m = sl(vals, 0)
    for d in range(1, q.kbuckets):
        m = (jnp.minimum if q.op == "min" else jnp.maximum)(m, sl(vals, d))
    return jnp.where(live, m, jnp.nan)


def _agg_block(ts, vals, q: GridQuery):
    """The *_over_time family on the aligned grid: no correction, no
    forward fill — K static sublane slices accumulate directly
    (reference: AggrOverTimeFunctions.scala sum/count/avg/min/max/last)."""
    if q.dense and q.op not in ("stddev", "stdvar"):
        return _agg_block_dense(ts, vals, q)
    if q.op in DENSE_ONLY_OPS:
        raise ValueError(f"grid op {q.op} requires the dense contract")
    ns = vals.shape[1]
    T = q.nsteps
    dt = vals.dtype
    fin = jnp.isfinite(vals)
    sl = _win_slicer(q, ns)
    shape = (T, ns)
    if q.op == "last":
        v2 = jnp.full(shape, jnp.nan, dt)
        for d in range(q.kbuckets):          # forward: last finite wins
            fd = sl(fin, d)
            v2 = jnp.where(fd, sl(vals, d), v2)
        return v2
    if q.op in ("stddev", "stdvar"):
        n, _mean, var = _masked_moments(vals, fin, sl, q.kbuckets, dt)
        var = jnp.where(n > 0, var, jnp.nan)
        return jnp.sqrt(var) if q.op == "stddev" else var
    s = jnp.zeros(shape, dt)
    c = jnp.zeros(shape, dt)
    mn = jnp.full(shape, jnp.inf, dt)
    mx = jnp.full(shape, -jnp.inf, dt)
    for d in range(q.kbuckets):
        fd = sl(fin, d)
        vd = sl(vals, d)
        c = c + fd.astype(dt)
        if q.op in ("sum", "avg"):
            s = s + jnp.where(fd, vd, 0.0)
        elif q.op == "min":
            mn = jnp.minimum(mn, jnp.where(fd, vd, jnp.inf))
        elif q.op == "max":
            mx = jnp.maximum(mx, jnp.where(fd, vd, -jnp.inf))
    if q.op == "count":
        return jnp.where(c > 0, c, jnp.nan)
    if q.op == "avg":
        return jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)
    if q.op == "min":
        return jnp.where(jnp.isfinite(mn), mn, jnp.nan)
    if q.op == "max":
        return jnp.where(jnp.isfinite(mx), mx, jnp.nan)
    return jnp.where(c > 0, s, jnp.nan)   # sum


def _linreg_block(ts, vals, steps0, q: GridQuery):
    """Least-squares slope/forecast over each window (reference:
    windows._linreg / Prometheus linearRegression with interceptTime =
    the range end).  x is seconds relative to the window end, recentered
    by +W/2 during accumulation so the f32 var/cov differences don't
    cancel catastrophically (the slope is shift-invariant)."""
    ns = vals.shape[1]
    dt = vals.dtype
    K = q.kbuckets
    sl = _win_slicer(q, ns)
    fin = jnp.isfinite(vals)
    tcol = jax.lax.broadcasted_iota(jnp.int32, (q.nsteps, ns), 0)
    hi = (steps0 + tcol * jnp.int32(q.gstep_ms * q.stride)).astype(dt)
    w_s = q.kbuckets * q.gstep_ms / 1000.0
    shift = jnp.asarray(w_s / 2.0, dt)
    n = jnp.zeros(hi.shape, dt)
    sx = jnp.zeros(hi.shape, dt)
    sy = jnp.zeros(hi.shape, dt)
    sxx = jnp.zeros(hi.shape, dt)
    sxy = jnp.zeros(hi.shape, dt)
    for d in range(K):
        fd = sl(fin, d)
        x = (sl(ts, d).astype(dt) - hi) / 1000.0 + shift
        y = sl(vals, d)
        fdt = fd.astype(dt)
        x = jnp.where(fd, x, 0.0)
        y = jnp.where(fd, y, 0.0)
        n = n + fdt
        sx = sx + x
        sy = sy + y
        sxx = sxx + x * x
        sxy = sxy + x * y
    nsafe = jnp.maximum(n, 1.0)
    cov = sxy - sx * sy / nsafe
    var = sxx - sx * sx / nsafe
    slope = cov / jnp.where(var == 0, 1.0, var)
    ok = (n >= 2) & (var > 0)
    if q.op == "deriv":
        return jnp.where(ok, slope, jnp.nan)
    # intercept at x=0 of the ORIGINAL axis (window end): undo the shift
    intercept = sy / nsafe - slope * (sx / nsafe - shift)
    out = intercept + slope * jnp.asarray(q.farg, dt)
    return jnp.where(ok, out, jnp.nan)


def _masked_moments(vals, fin, sl, K, dt):
    """Per-window (n, mean, var), centered on the per-lane grand mean
    exactly like windows.stdvar_stddev (the centering defeats the
    E[x^2]-E[x]^2 cancellation; variance itself is center-invariant).
    In f32 the device and host paths agree to ~1e-4 relative
    (summation-order rounding) — exact in the f64 reference."""
    nall = jnp.maximum(fin.sum(axis=0, keepdims=True), 1).astype(dt)
    center = jnp.where(fin, vals, 0.0).sum(axis=0, keepdims=True) / nall
    x = vals - center
    s1 = None
    s2 = None
    n = None
    for d in range(K):
        fd = sl(fin, d)
        xd = jnp.where(fd, sl(x, d), 0.0)
        fdt = fd.astype(dt)
        s1 = xd if s1 is None else s1 + xd
        s2 = xd * xd if s2 is None else s2 + xd * xd
        n = fdt if n is None else n + fdt
    nsafe = jnp.maximum(n, 1.0)
    mean_x = s1 / nsafe
    var = jnp.maximum(s2 / nsafe - mean_x * mean_x, 0.0)
    return n, center + mean_x, var   # mean: [1,ns]+[T,ns] broadcasts


def _zscore_block(ts, vals, q: GridQuery):
    """(last - mean) / stddev over the window (reference ZScoreChunked /
    windows.z_score, incl. the sd == 0 / n < 2 -> NaN rules)."""
    ns = vals.shape[1]
    dt = vals.dtype
    K = q.kbuckets
    sl = _win_slicer(q, ns)
    fin = jnp.isfinite(vals)
    n, mean, var = _masked_moments(vals, fin, sl, K, dt)
    sd = jnp.sqrt(var)
    lastv = None
    for d in range(K):
        fd = sl(fin, d)
        vd = sl(vals, d)
        lastv = jnp.where(fd, vd, jnp.nan if lastv is None else lastv)
    out = (lastv - mean) / jnp.where(sd == 0, 1.0, sd)
    return jnp.where((n >= 2) & (sd > 0), out, jnp.nan)


def _batcher_pairs(K: int) -> list:
    """Batcher odd-even mergesort compare-exchange pairs for K inputs —
    a data-independent sorting network generated at trace time."""
    pairs = []
    p = 1
    while p < K:
        k = p
        while k >= 1:
            for j in range(k % p, K - k, 2 * k):
                for i in range(0, min(k, K - j - k)):
                    if (i + j) // (2 * p) == (i + j + k) // (2 * p):
                        pairs.append((i + j, i + j + k))
            k //= 2
        p *= 2
    return pairs


def _sort_tiles(tiles: list) -> list:
    out = list(tiles)
    for a, b in _batcher_pairs(len(out)):
        lo = jnp.minimum(out[a], out[b])
        hi = jnp.maximum(out[a], out[b])
        out[a], out[b] = lo, hi
    return out


def _interp_rank(sorted_tiles: list, phi: float):
    """Linear-interpolated quantile over K sorted tiles: rank indices
    are STATIC for a static (phi, K) — two tile reads, no gathers.
    Matches jnp.nanquantile's linear method at n == K."""
    import math
    K = len(sorted_tiles)
    if math.isnan(phi):
        return jnp.full_like(sorted_tiles[0], jnp.nan)
    # Prometheus returns +Inf/-Inf for out-of-range phi (±Inf included)
    # rather than clamping (reference QuantileOverTimeFunction); mask to
    # live lanes happens in the caller
    if phi > 1.0:
        return jnp.full_like(sorted_tiles[0], jnp.inf)
    if phi < 0.0:
        return jnp.full_like(sorted_tiles[0], -jnp.inf)
    r = phi * (K - 1)
    lo_i, hi_i = int(math.floor(r)), int(math.ceil(r))
    frac = r - lo_i
    if lo_i == hi_i:
        return sorted_tiles[lo_i]
    return sorted_tiles[lo_i] * (1.0 - frac) + sorted_tiles[hi_i] * frac


def _sort_ops_block(ts, vals, q: GridQuery):
    """quantile_over_time / mad_over_time under the dense contract via a
    compile-time sorting network over the K window tiles (reference:
    QuantileOverTimeChunkedFunction / MedianAbsoluteDeviationOverTime)."""
    if not q.dense:
        raise ValueError(f"grid op {q.op} requires the dense contract")
    ns = vals.shape[1]
    K = q.kbuckets
    sl = _win_slicer(q, ns)
    tiles = [sl(vals, d) for d in range(K)]
    live = jnp.isfinite(tiles[0])
    s = _sort_tiles(tiles)
    if q.op == "quantile":
        out = _interp_rank(s, q.farg)
    else:                                     # mad
        med = _interp_rank(s, 0.5)
        dev = [jnp.abs(t - med) for t in tiles]
        out = _interp_rank(_sort_tiles(dev), 0.5)
    return jnp.where(live, out, jnp.nan)




def _holt_winters_block(ts, vals, q: GridQuery):
    """Double exponential smoothing under the dense contract: level
    seeds from the window's first row, trend from the first pair, then
    a K-step unrolled recurrence over the window tiles (reference
    HoltWintersFunction; identical math to windows.holt_winters with
    every sample present)."""
    if not q.dense:
        raise ValueError(f"grid op {q.op} requires the dense contract")
    ns = vals.shape[1]
    dt = vals.dtype
    K = q.kbuckets
    sl = _win_slicer(q, ns)
    if K < 2:
        return jnp.full((q.nsteps, ns), jnp.nan, dt)
    sf = jnp.asarray(q.farg, dt)
    tf = jnp.asarray(q.farg2, dt)
    s = sl(vals, 0)
    live = jnp.isfinite(s)
    b = jnp.zeros_like(s)
    for i in range(1, K):
        y = sl(vals, i)
        b_eff = (y - s) if i == 1 else b
        xn = sf * y + (1.0 - sf) * (s + b_eff)
        b = tf * (xn - s) + (1.0 - tf) * b_eff
        s = xn
    return jnp.where(live, s, jnp.nan)


def _timestamp_block(ts, vals, steps0, q: GridQuery):
    """timestamp() emitting seconds RELATIVE to each window's end: the
    magnitudes stay within the window span, exact in f32 (epoch-relative
    ms near the int32 limit would lose ~0.13 s to f32 rounding).  The
    serving path re-bases to absolute seconds in f64 on the host."""
    ns = vals.shape[1]
    dt = vals.dtype
    sl = _win_slicer(q, ns)
    fin = jnp.isfinite(vals)
    tcol = jax.lax.broadcasted_iota(jnp.int32, (q.nsteps, ns), 0)
    hi = steps0 + tcol * jnp.int32(q.gstep_ms * q.stride)
    if q.dense:
        live = jnp.isfinite(sl(vals, 0))
        rel = sl(ts, q.kbuckets - 1) - hi
        return jnp.where(live, rel.astype(dt) / 1000.0, jnp.nan)
    sel = jnp.full((q.nsteps, ns), _IBIG, ts.dtype)
    for d in range(q.kbuckets):              # forward: last finite wins
        fd = sl(fin, d)
        sel = jnp.where(fd, sl(ts, d), sel)
    return jnp.where(sel != _IBIG, (sel - hi).astype(dt) / 1000.0, jnp.nan)


def _rate_block(ts, vals, steps0, q: GridQuery):
    if q.op in ("irate", "idelta"):
        return _instant_pair_block(ts, vals, q)
    if q.op in ("quantile", "mad"):
        return _sort_ops_block(ts, vals, q)
    if q.op == "holt_winters":
        return _holt_winters_block(ts, vals, q)
    if q.op == "timestamp":
        return _timestamp_block(ts, vals, steps0, q)
    if q.op in ("deriv", "predict_linear"):
        return _linreg_block(ts, vals, steps0, q)
    if q.op == "zscore":
        return _zscore_block(ts, vals, q)
    if q.op == "delta":
        # gauge delta: extrapolated like rate but with NO counter
        # correction and NO zero-point clamp (reference delta_fn)
        if q.dense:
            stats = _window_stats_dense(ts, vals, vals, q)
        else:
            stats = _window_stats(ts, jnp.isfinite(vals), vals, q)
        return _extrapolate(*stats, steps0, q)
    if q.op not in ("rate", "increase"):
        return _agg_block(ts, vals, q)
    roll = lambda x, s: pltpu.roll(x, s, axis=0)
    if q.dense:
        # _rate_block only runs inside Pallas TPU kernels (the portable
        # dispatch lives in rate_grid_ref), so the MXU prefix is safe
        vcorr = _correct_dense_auto(vals, roll)
        stats = _window_stats_dense(ts, vals, vcorr, q)
    else:
        fin, vcorr = _correct_and_mask(ts, vals, roll)
        stats = _window_stats(ts, fin, vcorr, q)
    return _extrapolate(*stats, steps0, q)


# ops whose kernels never read the ts plane (window membership is the
# bucket index; the math uses values only): for these the Pallas wrappers
# do not stream ts at all — half the HBM traffic of a two-plane op
TS_FREE_OPS = frozenset(("quantile", "mad", "holt_winters", "zscore",
                         "last", "sum", "count", "avg", "min", "max",
                         "changes", "resets", "stddev", "stdvar"))


def _series_kernel(s0_ref, ts_ref, vals_ref, out_ref, *, q: GridQuery):
    out_ref[:] = _rate_block(ts_ref[:], vals_ref[:], s0_ref[0], q)


def _series_kernel_free(s0_ref, vals_ref, out_ref, *, q: GridQuery):
    out_ref[:] = _rate_block(None, vals_ref[:], s0_ref[0], q)


def _series_kernel_phase(s0_ref, ph_ref, vals_ref, out_ref, *,
                         q: GridQuery):
    roll = lambda x, s: pltpu.roll(x, s, axis=0)
    out, live_row = _phase_block_raw(ph_ref[0:1, :], vals_ref[:], q, roll,
                                     mxu=True)
    out_ref[:] = jnp.where(live_row, out, jnp.nan)


def _grouped_kernel(s0_ref, ts_ref, vals_ref, sum_ref, cnt_ref, *,
                    q: GridQuery):
    gi = pl.program_id(1)
    r = _rate_block(ts_ref[:], vals_ref[:], s0_ref[0], q)
    ok = jnp.isfinite(r)
    sum_ref[gi, :] = jnp.sum(jnp.where(ok, r, 0.0), axis=1)
    cnt_ref[gi, :] = jnp.sum(ok.astype(jnp.float32), axis=1)


def _grouped_kernel_free(s0_ref, vals_ref, sum_ref, cnt_ref, *,
                         q: GridQuery):
    gi = pl.program_id(1)
    r = _rate_block(None, vals_ref[:], s0_ref[0], q)
    ok = jnp.isfinite(r)
    sum_ref[gi, :] = jnp.sum(jnp.where(ok, r, 0.0), axis=1)
    cnt_ref[gi, :] = jnp.sum(ok.astype(jnp.float32), axis=1)


def _grouped_kernel_phase(s0_ref, ph_ref, vals_ref, sum_ref, cnt_ref, *,
                          q: GridQuery):
    """Grouped phase kernel: liveness is the [1, ns] row (dense), so the
    per-window finite count is nlive — a constant row — and the sum mask
    is a broadcast, not a [T, ns] isfinite pass."""
    gi = pl.program_id(1)
    roll = lambda x, s: pltpu.roll(x, s, axis=0)
    out, live_row = _phase_block_raw(ph_ref[0:1, :], vals_ref[:], q, roll,
                                     mxu=True)
    sum_ref[gi, :] = jnp.sum(jnp.where(live_row, out, 0.0), axis=1)
    nlive = jnp.sum(live_row.astype(jnp.float32))
    cnt_ref[gi, :] = jnp.full((q.nsteps,), nlive, jnp.float32)


def _smem():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _mode_for(q: GridQuery, phase) -> str:
    """Input-plane mode: 'free' ops stream only values; 'phase' streams
    values + one phase row; 'ts' streams both planes."""
    if q.op in TS_FREE_OPS:
        return "free"
    if _phase_mode(q, phase):
        return "phase"
    return "ts"


def _phase8(phase):
    """Phase as an [8, S] tile: Mosaic DMAs sublane-multiples; 8 rows of
    int32 per 1024-lane block is 32 KB — noise next to the vals plane."""
    ph = jnp.asarray(phase, jnp.int32)
    if ph.ndim == 1:
        ph = ph[None, :]
    return jnp.broadcast_to(ph[0:1, :], (8, ph.shape[-1]))


@functools.partial(devicewatch.jit, program="grid.rate_grid",
                   static_argnames=("q", "lanes", "interpret"))
def rate_grid(ts, vals, steps0, q: GridQuery, lanes: int = 1024,
              interpret: bool = False, phase=None):
    """Per-series windowed function over an aligned grid: [B, S] -> [T, S].

    ``steps0`` is a traced scalar (int32): differing query starts reuse
    one compiled kernel.  Row 0 must be the first bucket of the first
    window (see module docstring).

    ``phase`` ([S] int32, per-lane within-bucket scrape offset in
    (0, gstep]) activates the uniform-phase kernels for PHASE_OPS under
    the dense contract: the ts plane is not streamed at all.  For
    TS_FREE_OPS the ts plane is never streamed; ``ts`` may be None in
    both cases.
    """
    nb, ns = vals.shape
    if ns % lanes != 0 or ns == 0:
        raise ValueError(f"series count {ns} must be a non-zero multiple of "
                         f"lanes={lanes} (pad with NaN columns)")
    if nb < _rows_needed(q):
        raise ValueError(f"grid has {nb} rows; need (nsteps-1)*stride+K = "
                         f"{_rows_needed(q)}")
    if q.stride > 1:
        # Mosaic cannot lower strided sublane slices: run the stride-1
        # fine grid and subsample the output at the XLA level (the
        # extra windows cost VPU time but stay on the fast path)
        fine = rate_grid(ts, vals, steps0, _fine_query(q), lanes, interpret,
                         phase)
        return fine[::q.stride]
    mode = _mode_for(q, phase)
    vspec = pl.BlockSpec((nb, lanes), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
    if mode == "free":
        kern, extra, especs = _series_kernel_free, (), ()
    elif mode == "phase":
        kern = _series_kernel_phase
        extra = (_phase8(phase),)
        especs = (pl.BlockSpec((8, lanes), lambda i: (0, i),
                               memory_space=pltpu.VMEM),)
    else:
        kern, extra, especs = _series_kernel, (ts,), (vspec,)
    return pl.pallas_call(
        functools.partial(kern, q=q),
        interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((q.nsteps, ns), jnp.float32),
        grid=(ns // lanes,),
        in_specs=[_smem(), *especs, vspec],
        out_specs=pl.BlockSpec((q.nsteps, lanes), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
    )(jnp.asarray([steps0], jnp.int32), *extra, vals)


_GPS = 8  # groups per output block (output sublane granularity)


@functools.partial(devicewatch.jit, program="grid.rate_grid_grouped",
                   static_argnames=("q", "group_lanes", "interpret"))
def rate_grid_grouped(ts, vals, steps0, q: GridQuery,
                      group_lanes: int = 1024, interpret: bool = False,
                      phase=None):
    """Fused ``sum by (group)(rate(...))``: [B, S] -> (sum, count) [G, T].

    Series are pre-sorted by group and padded so group g occupies
    columns [g*group_lanes, (g+1)*group_lanes); G must be a multiple
    of 8 (host pads; padded groups come back with count 0).  ``phase``
    as in :func:`rate_grid`.
    """
    nb, ns = vals.shape
    ngroups = ns // group_lanes
    if ns % group_lanes != 0 or ngroups == 0 or ngroups % _GPS != 0:
        raise ValueError(
            f"series count {ns} must be (groups x group_lanes) with the "
            f"group count a non-zero multiple of {_GPS}; got "
            f"{ngroups} x {group_lanes} (pad groups with NaN columns and "
            f"the group list to a multiple of {_GPS})")
    if nb < _rows_needed(q):
        raise ValueError(f"grid has {nb} rows; need (nsteps-1)*stride+K = "
                         f"{_rows_needed(q)}")
    if q.stride > 1:
        s, c = rate_grid_grouped(ts, vals, steps0, _fine_query(q),
                                 group_lanes, interpret, phase)
        return s[:, ::q.stride], c[:, ::q.stride]
    mode = _mode_for(q, phase)
    vspec = pl.BlockSpec((nb, group_lanes),
                         lambda i, gi: (0, i * _GPS + gi),
                         memory_space=pltpu.VMEM)
    if mode == "free":
        kern, extra, especs = _grouped_kernel_free, (), ()
    elif mode == "phase":
        kern = _grouped_kernel_phase
        extra = (_phase8(phase),)
        especs = (pl.BlockSpec((8, group_lanes),
                               lambda i, gi: (0, i * _GPS + gi),
                               memory_space=pltpu.VMEM),)
    else:
        kern, extra, especs = _grouped_kernel, (ts,), (vspec,)
    s, c = pl.pallas_call(
        functools.partial(kern, q=q),
        interpret=interpret,
        out_shape=(jax.ShapeDtypeStruct((ngroups, q.nsteps), jnp.float32),
                   jax.ShapeDtypeStruct((ngroups, q.nsteps), jnp.float32)),
        grid=(ngroups // _GPS, _GPS),
        in_specs=[_smem(), *especs, vspec],
        out_specs=(pl.BlockSpec((_GPS, q.nsteps), lambda i, gi: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((_GPS, q.nsteps), lambda i, gi: (i, 0),
                                memory_space=pltpu.VMEM)),
    )(jnp.asarray([steps0], jnp.int32), *extra, vals)
    return s, c


# ---------------------------------------------------------------------------
# Compressed-resident kernels: on-device XOR-class decode fused into the
# grid compute, so one compiled program reads the ~2.5 B/sample packed
# planes from HBM instead of the 4 B/sample decoded plane (reference:
# serving compressed BinaryVectors in place, BlockManager.scala:142).
# Input layout contract: codecs/xorgrid.py (class sub-planes p8/p16/raw
# + [8, n] meta tiles: row 0 shift, row 1 first-value bits, row 2 phase).
# Everything runs in PACKED lane order — callers compose their existing
# host-side lane indirections with the pack's ``inv`` map; the device
# never gathers.
# ---------------------------------------------------------------------------


def _decode_packed(p_ref, m_ref):
    """In-VMEM XOR-class decode of one packed [B, L] tile to f32:
    widen -> per-lane shift -> log2(B) prefix-XOR roll scan -> XOR the
    first-row bits -> bitcast.  Raw (f32) tiles take the same path with
    shift 0, so every class decodes through one code shape."""
    p = p_ref[:]
    if p.dtype == jnp.float32:
        u = jax.lax.bitcast_convert_type(p, jnp.uint32)
    else:
        u = p.astype(jnp.uint32)
    z = m_ref[0:1, :].astype(jnp.uint32)
    u = u << z
    nb = u.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, u.shape, 0)
    sh = 1
    while sh < nb:
        u = jnp.where(row >= sh, u ^ pltpu.roll(u, sh, axis=0), u)
        sh *= 2
    first = jax.lax.bitcast_convert_type(m_ref[1:2, :], jnp.uint32)
    return jax.lax.bitcast_convert_type(u ^ first, jnp.float32)


def _decode_rows(p_ref, m_ref, q: GridQuery, row0: int):
    """Decode the full packed block (the prefix-XOR scan must start at
    block row 0) and take the query's row span as a STATIC sublane
    slice — ``row0`` is compile-time, which is what lets the slice land
    at arbitrary (non-8-aligned) offsets under Mosaic."""
    vals = _decode_packed(p_ref, m_ref)
    need = _rows_needed(q)
    return jax.lax.slice(vals, (row0, 0), (row0 + need, vals.shape[1]))


def _series_kernel_packed(s0_ref, m_ref, p_ref, out_ref, *, q: GridQuery,
                          row0: int, use_phase: bool):
    vals = _decode_rows(p_ref, m_ref, q, row0)
    if use_phase:
        roll = lambda x, s: pltpu.roll(x, s, axis=0)
        out, live_row = _phase_block_raw(m_ref[2:3, :], vals, q, roll,
                                         mxu=True)
        out_ref[:] = jnp.where(live_row, out, jnp.nan)
    else:
        out_ref[:] = _rate_block(None, vals, s0_ref[0], q)


def _grouped_kernel_packed(s0_ref, m_ref, p_ref, sum_ref, cnt_ref, *,
                           q: GridQuery, row0: int, use_phase: bool):
    gi = pl.program_id(1)
    vals = _decode_rows(p_ref, m_ref, q, row0)
    if use_phase:
        roll = lambda x, s: pltpu.roll(x, s, axis=0)
        out, live_row = _phase_block_raw(m_ref[2:3, :], vals, q, roll,
                                         mxu=True)
        sum_ref[gi, :] = jnp.sum(jnp.where(live_row, out, 0.0), axis=1)
        nlive = jnp.sum(live_row.astype(jnp.float32))
        cnt_ref[gi, :] = jnp.full((q.nsteps,), nlive, jnp.float32)
    else:
        r = _rate_block(None, vals, s0_ref[0], q)
        ok = jnp.isfinite(r)
        sum_ref[gi, :] = jnp.sum(jnp.where(ok, r, 0.0), axis=1)
        cnt_ref[gi, :] = jnp.sum(ok.astype(jnp.float32), axis=1)


def _packed_planes(packed: dict):
    """(packed plane, meta tile) pairs in packed (class) order, empty
    planes skipped."""
    out = []
    for key, mkey in (("p8", "m8"), ("p16", "m16"), ("p32", "m32"),
                      ("raw", "mraw")):
        p = packed.get(key)
        if p is None or p.shape[1] == 0:
            continue
        m = packed.get(mkey)
        if m is None:
            raise ValueError(f"packed plane {key} has no meta tile "
                             f"{mkey} (f64 packs carry no meta; the "
                             f"fused kernels are f32-only)")
        out.append((p, m))
    return out


def packed_width(packed: dict) -> int:
    """Total packed lane count (sum of class-plane widths, pads
    included) — the lane dimension of the fused kernels' output."""
    return sum(p.shape[1] for p, _m in _packed_planes(packed))


def _packed_check(packed: dict, q: GridQuery, row0: int, use_phase: bool):
    if use_phase:
        if not phase_eligible(q):
            raise ValueError(f"op {q.op} not phase-eligible (dense="
                             f"{q.dense}, K={q.kbuckets})")
    elif q.op not in TS_FREE_OPS:
        raise ValueError(f"packed kernels serve TS_FREE or phase-mode "
                         f"ops only; {q.op} needs a ts plane")
    for p, _m in _packed_planes(packed):
        if p.shape[0] < row0 + _rows_needed(q):
            raise ValueError(
                f"packed block has {p.shape[0]} rows; query needs rows "
                f"[{row0}, {row0 + _rows_needed(q)})")


def _plane_lane_tile(n: int) -> int:
    """Lane-tile width for one class plane: packed planes halve (p16)
    or quarter (p8) the bytes per lane, so coarser 1024-lane tiles keep
    DMA sizes up; odd tails fall back to one whole-plane block (Mosaic
    masks sub-128 lane dims)."""
    if n % 1024 == 0:
        return 1024
    if n % 128 == 0:
        return 128
    return n


@functools.partial(devicewatch.jit, program="grid.rate_grid_packed",
                   static_argnames=("q", "row0", "interpret", "use_phase"))
def rate_grid_packed(packed: dict, steps0, q: GridQuery, row0: int = 0,
                     interpret: bool = False, use_phase: bool = False):
    """Per-series windowed function over XOR-class packed residents:
    packed planes -> [T, packed_width] stepped values in PACKED lane
    order (map back through the pack's ``inv``).

    One pallas_call per class plane (uniform dtype per call); decode
    runs in VMEM, so HBM sees only the packed bytes.  ``row0`` is the
    first query row within the block and is STATIC — the decode scan
    must cover the whole block anyway, and a static offset keeps the
    window slices on Mosaic's fast path (one compiled kernel per
    (T, K, row0) signature; dashboards cycle row0 through at most
    BLOCK_BUCKETS values).  ``use_phase`` activates the uniform-phase
    kernels reading meta row 2; otherwise only TS_FREE ops are legal.
    """
    _packed_check(packed, q, row0, use_phase)
    if q.stride > 1:
        fine = rate_grid_packed(packed, steps0, _fine_query(q), row0,
                                interpret, use_phase)
        return fine[::q.stride]
    s0 = jnp.asarray([steps0], jnp.int32)
    outs = []
    for p, m in _packed_planes(packed):
        nb, n = p.shape
        lt = _plane_lane_tile(n)
        outs.append(pl.pallas_call(
            functools.partial(_series_kernel_packed, q=q, row0=row0,
                              use_phase=use_phase),
            interpret=interpret,
            out_shape=jax.ShapeDtypeStruct((q.nsteps, n), jnp.float32),
            grid=(n // lt,),
            in_specs=[_smem(),
                      pl.BlockSpec((8, lt), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((nb, lt), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((q.nsteps, lt), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        )(s0, m, p))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)


@functools.partial(devicewatch.jit,
                   program="grid.rate_grid_grouped_packed",
                   static_argnames=("q", "group_lanes", "row0", "interpret",
                                    "use_phase"))
def rate_grid_grouped_packed(packed: dict, steps0, q: GridQuery,
                             group_lanes: int = 1024, row0: int = 0,
                             interpret: bool = False,
                             use_phase: bool = True):
    """Fully fused ``sum by (group)(rate(...))`` over packed residents:
    packed planes -> (sum, count) [G, T], decode + window + grouped
    reduce in one kernel per class plane.

    Requires the GROUP-ALIGNED pack contract: every class plane's lane
    count is a multiple of ``group_lanes``, no group's lanes straddle a
    class boundary, and the pack carries NO alignment-pad lanes (the
    north-star layout packs whole groups via ``min_width``, so a
    uniform workload keeps its group order; mixed-class or padded
    layouts must use :func:`rate_grid_packed` + a segment reduce that
    drops pads through the group map).  Groups come back in
    packed-plane order.
    """
    _packed_check(packed, q, row0, use_phase)
    inv = packed.get("inv")
    if inv is not None and packed_width(packed) != inv.shape[0]:
        # a zero pad lane decodes to a constant finite 0.0 series: with
        # no group map to drop it, it would count as a live series in
        # its group (+1 count, skewed avg) — reject rather than corrupt
        raise ValueError(
            f"pack carries {packed_width(packed) - inv.shape[0]} "
            f"alignment-pad lanes; the fused grouped kernel has no "
            f"group map to drop them — use rate_grid_packed + a "
            f"segment reduce, or a min_width single-class pack")
    if q.stride > 1:
        s, c = rate_grid_grouped_packed(packed, steps0, _fine_query(q),
                                        group_lanes, row0, interpret,
                                        use_phase)
        return s[:, ::q.stride], c[:, ::q.stride]
    s0 = jnp.asarray([steps0], jnp.int32)
    sums, cnts = [], []
    for p, m in _packed_planes(packed):
        nb, n = p.shape
        ng = n // group_lanes
        if n % group_lanes != 0 or ng == 0 or ng % _GPS != 0:
            raise ValueError(
                f"packed plane width {n} must be (groups x "
                f"{group_lanes}) with the group count a multiple of "
                f"{_GPS} — use the group-aligned pack layout")
        s, c = pl.pallas_call(
            functools.partial(_grouped_kernel_packed, q=q, row0=row0,
                              use_phase=use_phase),
            interpret=interpret,
            out_shape=(jax.ShapeDtypeStruct((ng, q.nsteps), jnp.float32),
                       jax.ShapeDtypeStruct((ng, q.nsteps), jnp.float32)),
            grid=(ng // _GPS, _GPS),
            in_specs=[_smem(),
                      pl.BlockSpec((8, group_lanes),
                                   lambda i, gi: (0, i * _GPS + gi),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((nb, group_lanes),
                                   lambda i, gi: (0, i * _GPS + gi),
                                   memory_space=pltpu.VMEM)],
            out_specs=(pl.BlockSpec((_GPS, q.nsteps),
                                    lambda i, gi: (i, 0),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((_GPS, q.nsteps),
                                    lambda i, gi: (i, 0),
                                    memory_space=pltpu.VMEM)),
        )(s0, m, p)
        sums.append(s)
        cnts.append(c)
    if len(sums) == 1:
        return sums[0], cnts[0]
    return jnp.concatenate(sums, axis=0), jnp.concatenate(cnts, axis=0)


# ---------------------------------------------------------------------------
# Compressed-resident HISTOGRAM kernels (ISSUE 14): decode bucket planes
# in VMEM and reduce the bucket dimension with BANDED MXU matmuls.
#
# Input layout contract (codecs/xorgrid.py ``pack_vals(stride=hb)`` over
# the device store's hist group-slot plane, devicestore.hist_slot_garr):
# column ``s*hb + j`` holds series s's cumulative bucket j, a series'
# ``hb`` columns classify together and stay contiguous in bucket order.
# The fused grouped kernel additionally requires the group-aligned
# single-class identity pack (min_width, no pads) — same contract as
# :func:`rate_grid_grouped_packed`, with ``group_lanes % hb == 0``.
#
# The per-bucket window compute is the SAME code path as the scalar
# kernels (each bucket column is an independent counter lane, incl. the
# banded ``_corr_v1_delta_banded`` correction on K-heavy shapes); what
# is hist-specific is the in-kernel bucket reduce: summing series within
# a group PER BUCKET is a banded 0/1 matmul ``M[j, c] = (c mod hb == j)``
# applied to the [T, group_lanes] stepped tile — the
# ``_corr_v1_delta_banded`` trick (arXiv:2112.09017's reductions-as-
# banded-matmuls) restated on the bucket axis, so the reduce runs on the
# MXU instead of a serialized scatter-add.
# ---------------------------------------------------------------------------


def _hb8(hb: int) -> int:
    """Bucket count padded to the sublane multiple: output blocks are
    [hb8, T] per group, so dynamic sublane offsets never appear."""
    return -(-hb // 8) * 8


def _hist_grouped_kernel_packed(s0_ref, m_ref, p_ref, sum_ref, cnt_ref, *,
                                q: GridQuery, row0: int, use_phase: bool,
                                hb: int):
    """One group per kernel instance: decode the group's packed
    [nb, group_lanes] tile, run the windowed op per bucket column, and
    band-reduce series into [hb8, T] per-bucket (sum, count) planes."""
    vals = _decode_rows(p_ref, m_ref, q, row0)
    if use_phase:
        roll = lambda x, s: pltpu.roll(x, s, axis=0)
        out, live_row = _phase_block_raw(m_ref[2:3, :], vals, q, roll,
                                         mxu=True)
        vz = jnp.where(live_row, out, 0.0)
        ok = jnp.broadcast_to(live_row, out.shape).astype(jnp.float32)
    else:
        r = _rate_block(None, vals, s0_ref[0], q)
        fin = jnp.isfinite(r)
        vz = jnp.where(fin, r, 0.0)
        ok = fin.astype(jnp.float32)
    gl = vz.shape[1]
    hb8 = sum_ref.shape[0]
    j = jax.lax.broadcasted_iota(jnp.int32, (hb8, gl), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (hb8, gl), 1)
    band = (c % hb == j).astype(jnp.float32)      # [hb8, gl] banded 0/1
    hp = jax.lax.Precision.HIGHEST
    dims = (((1,), (1,)), ((), ()))
    sum_ref[:, :] = jax.lax.dot_general(band, vz, dims, precision=hp,
                                        preferred_element_type=jnp.float32)
    cnt_ref[:, :] = jax.lax.dot_general(band, ok, dims, precision=hp,
                                        preferred_element_type=jnp.float32)


@functools.partial(devicewatch.jit,
                   program="grid.hist_grid_grouped_packed",
                   static_argnames=("q", "hb", "group_lanes", "row0",
                                    "interpret", "use_phase"))
def hist_grid_grouped_packed(packed: dict, steps0, q: GridQuery, hb: int,
                             group_lanes: int = 1024, row0: int = 0,
                             interpret: bool = False,
                             use_phase: bool = True):
    """Fully fused ``sum by (g)(rate(latency_bucket[w]))`` over packed
    HISTOGRAM residents: packed bucket planes -> (sum, count)
    ``[G*hb, T]`` — decode, per-bucket window compute, and the banded-
    MXU bucket reduce in ONE kernel per class plane.  Output slot
    ``g*hb + j`` is group g's cumulative bucket j (the
    ``hist_slot_garr`` layout ``hist_state_from_planes`` consumes).

    Requires the hist group-aligned pack contract: a single-class
    identity-order pack (``pack_vals(stride=hb, min_width=...)``, no
    alignment pads), ``group_lanes % hb == 0``, and every group's
    ``group_lanes`` columns contiguous.  Mixed-class hist packs must
    use :func:`rate_grid_packed` + a segment reduce instead."""
    if group_lanes % hb != 0:
        raise ValueError(f"group_lanes {group_lanes} not a multiple of "
                         f"the bucket count {hb}")
    _packed_check(packed, q, row0, use_phase)
    inv = packed.get("inv")
    if inv is not None and packed_width(packed) != inv.shape[0]:
        raise ValueError(
            "pack carries alignment-pad lanes; the fused hist grouped "
            "kernel has no group map to drop them — use the identity "
            "min_width hist pack")
    if q.stride > 1:
        s, c = hist_grid_grouped_packed(packed, steps0, _fine_query(q), hb,
                                        group_lanes, row0, interpret,
                                        use_phase)
        return s[:, ::q.stride], c[:, ::q.stride]
    s0 = jnp.asarray([steps0], jnp.int32)
    hb8 = _hb8(hb)
    sums, cnts = [], []
    for p, m in _packed_planes(packed):
        nb, n = p.shape
        ng = n // group_lanes
        if n % group_lanes != 0 or ng == 0:
            raise ValueError(
                f"packed plane width {n} must be a whole number of "
                f"{group_lanes}-column groups — use the hist "
                f"group-aligned pack layout")
        s, c = pl.pallas_call(
            functools.partial(_hist_grouped_kernel_packed, q=q, row0=row0,
                              use_phase=use_phase, hb=hb),
            interpret=interpret,
            out_shape=(jax.ShapeDtypeStruct((ng * hb8, q.nsteps),
                                            jnp.float32),
                       jax.ShapeDtypeStruct((ng * hb8, q.nsteps),
                                            jnp.float32)),
            grid=(ng,),
            in_specs=[_smem(),
                      pl.BlockSpec((8, group_lanes), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((nb, group_lanes), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=(pl.BlockSpec((hb8, q.nsteps), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((hb8, q.nsteps), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM)),
        )(s0, m, p)
        sums.append(s)
        cnts.append(c)
    s = sums[0] if len(sums) == 1 else jnp.concatenate(sums, axis=0)
    c = cnts[0] if len(cnts) == 1 else jnp.concatenate(cnts, axis=0)
    if hb8 != hb:
        G = s.shape[0] // hb8
        s = s.reshape(G, hb8, -1)[:, :hb, :].reshape(G * hb, -1)
        c = c.reshape(G, hb8, -1)[:, :hb, :].reshape(G * hb, -1)
    return s, c


@functools.partial(devicewatch.jit,
                   program="grid.hist_quantile_grid_packed",
                   static_argnames=("q", "phi", "hb", "group_lanes",
                                    "row0", "interpret", "use_phase"))
def hist_quantile_grid_packed(packed: dict, steps0, tops, q: GridQuery,
                              phi: float, hb: int,
                              group_lanes: int = 1024, row0: int = 0,
                              interpret: bool = False,
                              use_phase: bool = True):
    """Fused ``histogram_quantile(phi, sum by (g)(rate(...)))``: the
    packed hist kernel above feeds the le-interpolation IN THE SAME
    compiled program, so only the final ``[G, T]`` quantile plane ever
    leaves the device — no per-bucket partial crosses the host link.
    ``tops`` is the [hb] cumulative bucket upper bounds (le values)."""
    from filodb_tpu.ops import histogram_ops

    s, c = hist_grid_grouped_packed(packed, steps0, q, hb, group_lanes,
                                    row0, interpret, use_phase)
    T = s.shape[1]
    G = s.shape[0] // hb
    hist_sum = s.reshape(G, hb, T).transpose(0, 2, 1)     # [G, T, hb]
    out = histogram_ops.hist_quantile(jnp.asarray(tops), hist_sum,
                                      phi)                # [G, T]
    nlive = c.reshape(G, hb, T)[:, hb - 1, :]             # total bucket
    return jnp.where(nlive > 0, out, jnp.nan)


# ---------------------------------------------------------------------------
# Generic columnar event scan -> filter -> topK (ISSUE 14): the GDELT
# shape.  Each event stream is a lane of a (packed) numeric column
# plane; the fused program decodes the value column in VMEM, runs the
# windowed aggregate, masks lanes through an optional predicate on a
# SECOND column (scanned the same fused way), reduces lanes into groups
# with a one-hot MXU matmul (the banded-reduce family: group lanes are
# contiguous, so the 0/1 matrix is banded), and ranks groups with
# top_k — one compiled program, only [T, k] values + indices leave the
# device.
# ---------------------------------------------------------------------------

_FILTER_OPS = {
    "gt": lambda v, t: v > t, "ge": lambda v, t: v >= t,
    "lt": lambda v, t: v < t, "le": lambda v, t: v <= t,
    "eq": lambda v, t: v == t, "ne": lambda v, t: v != t,
}

# one-hot group reduce beyond this many groups costs too much memory
# (the [lanes, G] operand) — same cap and segment_sum fallback as the
# devicestore's _grouped_reduce_impl
_TOPK_ONEHOT_MAX_G = 2048


@functools.partial(devicewatch.jit,
                   program="grid.event_topk_grid_packed",
                   static_argnames=("q", "k", "num_groups", "filt_op",
                                    "filt_q", "row0", "interpret",
                                    "largest", "group_width"))
def event_topk_grid_packed(packed: dict, steps0, q: GridQuery, k: int,
                           garr, num_groups: int,
                           filt_packed: Optional[dict] = None,
                           filt_op: str = "gt", filt_thresh=0.0,
                           filt_q: Optional[GridQuery] = None,
                           filt_pos=None, row0: int = 0,
                           interpret: bool = False, largest: bool = True,
                           group_width: int = 0):
    """``topk(k, agg by (g)(fn(value_col[w])))`` with an optional scan
    filter on a second column, over packed columnar residents.

    - ``packed``: the value column's XOR-class planes (packed order).
    - ``garr``: [packed_width] int32 lane -> group slot in PACKED order
      (``num_groups`` = drop bucket for pad/unrequested lanes).
    - ``group_width``: when every group is ``group_width`` CONTIGUOUS
      packed lanes (the banded layout: group g = lanes [g*W, (g+1)*W)),
      pass it and ``garr=None`` — the reduce becomes a reshape-sum with
      no [lanes, G] one-hot operand at all (the memory-free banded
      form; the bench's 256k-lane table would otherwise stream a
      multi-GiB one-hot).  A general ``garr`` uses the one-hot MXU
      matmul up to ``_TOPK_ONEHOT_MAX_G`` groups and segment_sum past
      it (the devicestore ``_grouped_reduce_impl`` policy).
    - ``filt_packed``/``filt_op``/``filt_thresh``: keep only lanes whose
      filter-column window value satisfies ``filt_op(v, thresh)``
      (ops: gt/ge/lt/le/eq/ne); ``filt_q`` defaults to ``q`` with the
      same window; ``filt_pos`` ([packed_width] int32) maps the VALUE
      pack's lane order into the FILTER pack's when the two columns
      packed with different layouts (identity packs need none).
    - returns ``(vals [T, k], idx [T, k])``: per step the top-k group
      sums (``largest=False`` ranks smallest) and their group slots;
      exhausted ranks come back NaN / -1.
    """
    if filt_op not in _FILTER_OPS:
        raise ValueError(f"unknown filter op {filt_op!r} "
                         f"(have {sorted(_FILTER_OPS)})")
    if group_width and garr is not None:
        raise ValueError("pass garr OR group_width, not both")
    stepped = rate_grid_packed(packed, steps0, q, row0=row0,
                               interpret=interpret)          # [T, n]
    if filt_packed is not None:
        fq = filt_q if filt_q is not None else q
        fstep = rate_grid_packed(filt_packed, steps0, fq, row0=row0,
                                 interpret=interpret)
        if filt_pos is not None:
            fstep = fstep[:, filt_pos]
        keep = _FILTER_OPS[filt_op](fstep,
                                    jnp.asarray(filt_thresh, fstep.dtype))
        stepped = jnp.where(keep, stepped, jnp.nan)
    fin = jnp.isfinite(stepped)
    vz = jnp.where(fin, stepped, 0.0)
    T, n = stepped.shape
    if group_width:
        if n != num_groups * group_width:
            raise ValueError(
                f"packed width {n} != num_groups {num_groups} x "
                f"group_width {group_width}")
        sums = vz.reshape(T, num_groups, group_width).sum(2).T
        cnts = fin.reshape(T, num_groups, group_width) \
            .sum(2).T.astype(stepped.dtype)
    elif num_groups + 1 <= _TOPK_ONEHOT_MAX_G:
        garr = jnp.asarray(garr, jnp.int32)
        onehot = (garr[:, None] ==
                  jnp.arange(num_groups, dtype=jnp.int32)[None, :]
                  ).astype(stepped.dtype)                    # [n, G]
        hp = jax.lax.Precision.HIGHEST
        sums = jnp.matmul(onehot.T, vz.T, precision=hp)      # [G, T]
        cnts = jnp.matmul(onehot.T, fin.astype(stepped.dtype).T,
                          precision=hp)
    else:
        garr = jnp.asarray(garr, jnp.int32)
        sums = jax.ops.segment_sum(vz.T, garr,
                                   num_groups + 1)[:num_groups]
        cnts = jax.ops.segment_sum(fin.astype(stepped.dtype).T, garr,
                                   num_groups + 1)[:num_groups]
    sentinel = -jnp.inf if largest else jnp.inf
    ranked = jnp.where(cnts > 0, sums, sentinel).T           # [T, G]
    if not largest:
        ranked = -ranked
    vals, idx = jax.lax.top_k(ranked, k)
    live = jnp.isfinite(vals)
    if not largest:
        vals = -vals
    return (jnp.where(live, vals, jnp.nan),
            jnp.where(live, idx, -1))


# ---------------------------------------------------------------------------
# Pure-XLA reference implementation (CPU fallback + test oracle)
# ---------------------------------------------------------------------------

def rate_grid_ref(ts, vals, steps0: int, q: GridQuery, phase=None):
    """Same semantics as :func:`rate_grid`, in portable jnp.  ``phase``
    activates the collapsed uniform-phase formulation (used as the CPU
    serving path and as the oracle for the phase kernels); ``ts`` may
    then be None."""
    def roll(x, s):
        return jnp.concatenate([x[-s:], x[:-s]], axis=0)
    if _phase_mode(q, phase):
        ph = jnp.asarray(phase, jnp.int32)
        if ph.ndim == 1:
            ph = ph[None, :]
        return _phase_block(ph[0:1, :], vals, q, roll, mxu=False)
    if q.op in ("irate", "idelta"):
        return _instant_pair_block(ts, vals, q)
    if q.op in ("quantile", "mad"):
        return _sort_ops_block(ts, vals, q)
    if q.op == "holt_winters":
        return _holt_winters_block(ts, vals, q)
    if q.op == "timestamp":
        return _timestamp_block(ts, vals, jnp.int32(steps0), q)
    if q.op in ("deriv", "predict_linear"):
        return _linreg_block(ts, vals, jnp.int32(steps0), q)
    if q.op == "zscore":
        return _zscore_block(ts, vals, q)
    if q.op == "delta":
        if q.dense:
            stats = _window_stats_dense(ts, vals, vals, q)
        else:
            stats = _window_stats(ts, jnp.isfinite(vals), vals, q)
        return _extrapolate(*stats, jnp.int32(steps0), q)
    if q.op not in ("rate", "increase"):
        return _agg_block(ts, vals, q)
    if q.dense:
        vcorr = _correct_dense(vals, roll)
        stats = _window_stats_dense(ts, vals, vcorr, q)
    else:
        fin, vcorr = _correct_and_mask(ts, vals, roll)
        stats = _window_stats(ts, fin, vcorr, q)
    return _extrapolate(*stats, jnp.int32(steps0), q)


def rate_grid_auto(ts, vals, steps0, q: GridQuery, lanes: int = 1024,
                   phase=None):
    """Pallas on TPU backends, portable reference elsewhere.  ``steps0``
    may be a traced scalar (this runs under the serving path's fused
    jit program)."""
    if on_tpu_backend() and vals.shape[1] % lanes == 0:
        return rate_grid(ts, vals, steps0, q, lanes, phase=phase)
    return rate_grid_ref(ts, vals, steps0, q, phase=phase)


def rate_grid_batch_impl(ts_b, vals_b, steps0s, q: GridQuery,
                         lanes: int = 1024, phase=None):
    """Fleet-batched grid kernel (ISSUE 20): vmap of
    :func:`rate_grid_auto` over a leading MEMBER axis — B shape-
    compatible queries against B pre-sliced views of the same resident
    planes, one device program instead of B (the DrJAX vmap-over-
    clients idiom).  ``ts_b``/``vals_b`` are ``[B, rows, cols]``
    (``ts_b`` None in phase mode), ``steps0s`` is the ``[B]`` vector
    of per-member first window ends; ``phase`` is shared and
    broadcast.  Plain function: the serving path fuses it into its own
    jitted program (memstore/devicestore.py ``series_batch``/
    ``grouped_batch``) so slicing + kernel + readback stay ONE
    dispatch."""
    if ts_b is None:
        return jax.vmap(lambda v, s: rate_grid_auto(
            None, v, s, q, lanes, phase=phase))(vals_b, steps0s)
    return jax.vmap(lambda t, v, s: rate_grid_auto(
        t, v, s, q, lanes, phase=phase))(ts_b, vals_b, steps0s)


@functools.partial(devicewatch.jit, program="grid.rate_grid_batch",
                   static_argnames=("q", "lanes"))
def rate_grid_batch(ts_b, vals_b, steps0s, q: GridQuery,
                    lanes: int = 1024, phase=None):
    """Standalone jitted batched entry over already-materialized
    planes (tests, direct grid users).  The serving path does NOT call
    this — it inlines :func:`rate_grid_batch_impl` into the fused
    device-store programs to avoid a second dispatch."""
    return rate_grid_batch_impl(ts_b, vals_b, steps0s, q, lanes,
                                phase=phase)


MAX_K_BUCKETS = 64   # K-unrolled kernel passes; caps the compile cost
MAX_GRID_ROWS = 1024  # input rows per query: VMEM tile height bound (TPU)
# any backend: bounds blocks staged/assembled per query (a coarse step
# over a fine cadence can otherwise span millions of buckets)
MAX_GRID_SPAN_ROWS = 16_384

# ops whose DENSE kernel is K-free (rate/increase: window stats are two
# static slices; last: one slice; count: a constant; irate/idelta: the
# window's last two rows) — for these a proven-dense query may use any
# K up to the row bound, which keeps high-frequency data (5m window
# over 1s scrapes -> K=300) on the fast path.  sum/avg/min/max/stddev/
# changes/... accumulate K slices even when dense, so they keep the
# unroll cap.
K_FREE_DENSE_OPS = frozenset(("rate", "increase", "last", "count",
                              "irate", "idelta", "delta", "timestamp"))

# ops grid-served ONLY under the proven dense contract (the general
# scan path serves otherwise): consecutive-sample adjacency ops
# (changes/resets/irate/idelta), sort-based ops where NaN poisons a
# min/max sorting network (quantile/mad), and recurrence ops whose
# reference semantics SKIP NaN samples — the unrolled kernel is only
# equivalent when every window slot is filled (holt_winters)
DENSE_ONLY_OPS = frozenset(("changes", "resets", "irate", "idelta",
                            "quantile", "mad", "holt_winters"))

# sort-based ops run a Batcher network of O(K log^2 K) compare-exchanges
# over [T, L] tiles; cap K so compile time and VPU work stay sane
SORT_OPS_MAX_K = 32


def max_k_for(op: str, dense: bool) -> int:
    if op in ("quantile", "mad"):
        return SORT_OPS_MAX_K
    return MAX_GRID_ROWS if dense and op in K_FREE_DENSE_OPS \
        else MAX_K_BUCKETS


def supports_grid(window_ms: int, step_ms: int, gstep_ms: int,
                  nsteps: int = 1, max_k: int = MAX_K_BUCKETS) -> bool:
    """Host-side check: can the aligned fast path serve this query?
    The query step may be any multiple of the bucket width (stride
    serving — dashboards commonly step coarser than the scrape
    cadence).  ``max_k`` caps K = window/gstep — pass
    ``max_k_for(op, dense)`` to allow large windows for the K-free
    dense ops; the general kernels unroll K static slice passes, so an
    uncapped K there would pay a huge one-off compile on the most
    interactive query shape.  Total input rows are capped by the VMEM
    tile height.  Beyond the caps the general path serves."""
    if not (window_ms > 0 and gstep_ms > 0 and step_ms > 0
            and step_ms % gstep_ms == 0 and window_ms % gstep_ms == 0
            and window_ms // gstep_ms <= max_k):
        return False
    stride = step_ms // gstep_ms
    rows = (nsteps - 1) * stride + window_ms // gstep_ms
    if rows > MAX_GRID_SPAN_ROWS:
        return False    # block-assembly bound, any backend
    if not on_tpu_backend():
        return True     # portable reference path: no VMEM tile bound
    return rows <= MAX_GRID_ROWS


# ---------------------------------------------------------------------------
# M4 visualization downsampling (ISSUE 16): per-pixel-bin min/max/
# first/last selection (the M4 aggregation of Jugel et al., adopted by
# tsdownsample/MinMaxLTTB, arXiv:2307.05389).  A T-step series split
# into P pixel bins keeps <= 4 points per bin — everything a width-P
# panel can render — so a year-long query returns ~4P points instead
# of millions.  Pure SELECTION, no arithmetic: the kernel output is
# bit-equal to a NumPy oracle by construction.
# ---------------------------------------------------------------------------

#: m4 plane order along output axis 1: values then LOCAL row indices
M4_PLANES = ("vmin", "vmax", "vfirst", "vlast",
             "imin", "imax", "ifirst", "ilast")


def _m4_planes(v, idx, big):
    """Shared selection math over one bin axis (rows): 8 [S]-planes.
    Ties on min/max resolve to the FIRST occurrence; empty bins yield
    NaN values and -1 indices.  Works on [W, S] blocks (kernel) and
    batched [P, W, S] (reference) alike via ``axis=-2``."""
    fin = jnp.isfinite(v)
    vmin = jnp.min(jnp.where(fin, v, jnp.inf), axis=-2)
    vmax = jnp.max(jnp.where(fin, v, -jnp.inf), axis=-2)
    ifirst = jnp.min(jnp.where(fin, idx, big), axis=-2)
    ilast = jnp.max(jnp.where(fin, idx, -1), axis=-2)
    imin = jnp.min(jnp.where(fin & (v == jnp.expand_dims(vmin, -2)),
                             idx, big), axis=-2)
    imax = jnp.min(jnp.where(fin & (v == jnp.expand_dims(vmax, -2)),
                             idx, big), axis=-2)
    vfirst = jnp.sum(jnp.where(idx == jnp.expand_dims(ifirst, -2), v, 0.0),
                     axis=-2)
    vlast = jnp.sum(jnp.where(idx == jnp.expand_dims(ilast, -2), v, 0.0),
                    axis=-2)
    empty = ifirst == big
    nanv = jnp.float32(jnp.nan)
    neg1 = jnp.float32(-1)
    return (jnp.where(empty, nanv, vmin), jnp.where(empty, nanv, vmax),
            jnp.where(empty, nanv, vfirst), jnp.where(empty, nanv, vlast),
            jnp.where(empty, neg1, imin.astype(jnp.float32)),
            jnp.where(empty, neg1, imax.astype(jnp.float32)),
            jnp.where(empty, neg1, ifirst.astype(jnp.float32)),
            jnp.where(empty, neg1, ilast.astype(jnp.float32)))


def _m4_kernel(v_ref, out_ref):
    """One (pixel bin, lane block): [wpad, L] -> [1, 8, L].  Rows past
    the bin's true width are NaN padding and never selected."""
    v = v_ref[...]
    idx = jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
    planes = _m4_planes(v, idx, jnp.int32(_IBIG))
    for k in range(8):
        out_ref[0, k, :] = planes[k]


def _m4_bin_shape(nsteps: int, pixels: int) -> tuple[int, int]:
    """(bin width W, sublane-padded width) for T steps over P bins."""
    w = -(-nsteps // pixels)
    return w, -(-w // 8) * 8


@functools.partial(devicewatch.jit, program="grid.m4_grid",
                   static_argnames=("pixels", "lanes", "interpret"))
def m4_grid(vals, pixels: int, lanes: int = 128,
            interpret: bool = False):
    """M4 pixel-bin selection: time-major ``vals [T, S]`` -> planes
    ``[P, 8, S]`` in :data:`M4_PLANES` order.  Index planes are LOCAL
    to the bin (global row = ``p * W + local``, ``W = ceil(T/P)``);
    NaN steps are absent samples, bins with no finite sample come back
    NaN / -1.  Banded layout: time on sublanes (one bin's rows per
    block), series on lanes — S must be a multiple of ``lanes`` (pad
    with NaN columns)."""
    nsteps, ns = vals.shape
    if ns % lanes != 0 or ns == 0:
        raise ValueError(f"series count {ns} must be a non-zero multiple "
                         f"of lanes={lanes} (pad with NaN columns)")
    if pixels < 1:
        raise ValueError(f"pixels must be >= 1, got {pixels}")
    w, wpad = _m4_bin_shape(nsteps, pixels)
    v = jnp.asarray(vals, jnp.float32)
    # host-side (XLA) re-banding: pad T to P*W, split bins, pad each
    # bin's rows to a sublane multiple, flatten back to 2-D so the
    # kernel sees one aligned [wpad, lanes] tile per (bin, lane block)
    v = jnp.pad(v, ((0, pixels * w - nsteps), (0, 0)),
                constant_values=jnp.nan)
    v = v.reshape(pixels, w, ns)
    v = jnp.pad(v, ((0, 0), (0, wpad - w), (0, 0)),
                constant_values=jnp.nan)
    v = v.reshape(pixels * wpad, ns)
    return pl.pallas_call(
        _m4_kernel,
        interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((pixels, 8, ns), jnp.float32),
        grid=(ns // lanes, pixels),
        in_specs=[pl.BlockSpec((wpad, lanes), lambda i, p: (p, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, 8, lanes), lambda i, p: (p, 0, i),
                               memory_space=pltpu.VMEM),
    )(v)


def m4_grid_ref(vals, pixels: int):
    """Same semantics as :func:`m4_grid` in portable jnp (CPU serving
    path + test oracle's device-side twin).  Selection only — the
    outputs are bit-identical to the kernel's."""
    nsteps, ns = vals.shape
    if ns == 0 or nsteps == 0:
        raise ValueError(f"empty input {vals.shape}")
    if pixels < 1:
        raise ValueError(f"pixels must be >= 1, got {pixels}")
    w, _wpad = _m4_bin_shape(nsteps, pixels)
    v = jnp.asarray(vals, jnp.float32)
    v = jnp.pad(v, ((0, pixels * w - nsteps), (0, 0)),
                constant_values=jnp.nan)
    v = v.reshape(pixels, w, ns)
    idx = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
    return jnp.stack(_m4_planes(v, idx, jnp.int32(_IBIG)), axis=1)


def m4_grid_auto(vals, pixels: int, lanes: int = 128):
    """Pallas on TPU backends (when the series axis tiles), portable
    reference elsewhere."""
    if on_tpu_backend() and vals.shape[1] % lanes == 0 and vals.shape[1]:
        return m4_grid(vals, pixels, lanes)
    return m4_grid_ref(vals, pixels)
