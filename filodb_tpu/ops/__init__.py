"""Device kernel library: windowed range functions + aggregations.

The TPU-native replacement for the reference's per-row hot loops
(ChunkedWindowIterator + RangeFunction + RowAggregator; reference:
query/exec/PeriodicSamplesMapper.scala:184-459,
query/exec/rangefn/RangeFunction.scala, query/exec/aggregator/*).

Everything here is jit-compatible JAX operating on padded dense batches
``[series, rows]`` with an output step grid ``[T]``:

- window bounds come from vmapped ``searchsorted`` (replacing per-window
  binarySearch/ceilingIndex);
- O(1)-per-window functions (sum/count/avg/rate/stddev/changes/...) read
  prefix-sum differences instead of iterating rows;
- irregular functions (min/max/quantile/holt_winters/...) gather bounded
  per-window row tiles and reduce along the tile axis;
- cross-series grouping is a host-computed segment-id vector + on-device
  segment reductions (psum-able across mesh shards).
"""

from filodb_tpu.ops import windows, aggregate  # noqa: F401
