"""Windowed range functions as batched JAX kernels.

Semantics match the reference's PeriodicSamplesMapper windows — for each
output step ``t`` the window is ``(t - window, t]``, start exclusive / end
inclusive (reference: query/exec/PeriodicSamplesMapper.scala:323-344) — and
Prometheus' extrapolation rules for rate/increase/delta (reference:
query/exec/rangefn/RateFunctions.scala:10-80 extrapolatedRate, kept
"consistent with Prometheus" per its own comment).

Formulation: instead of the reference's per-window row iteration
(ChunkedRangeFunction.addChunks doing binarySearch + a row loop per window),
every kernel here computes ALL windows of ALL series at once:

- ``window_bounds``: vmapped searchsorted -> [S, T] first/last row indices.
- prefix-path kernels: running sums over the row axis; each window is two
  gathers and a subtract (O(1) per window, O(R) total — asymptotically
  better than the reference's O(windows * rows_per_window)).
- gather-path kernels (min/max/quantile/...): bounded per-window row tiles
  [S, T, W] reduced along W on the VPU.

All kernels are shape-polymorphic in S (series), R (rows), T (steps) and are
jit-compiled per (R, T, W) bucket.  NaN is "no sample" for gauges; padded
rows carry ts=+inf / value=NaN and drop out of every path naturally.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class StepRange(NamedTuple):
    """Regular output grid: steps at start, start+step, ..., end (inclusive),
    like the reference's RangeParams."""

    start: int  # ms
    end: int    # ms
    step: int   # ms

    @property
    def num_steps(self) -> int:
        return (self.end - self.start) // self.step + 1

    def timestamps(self, dtype=None):
        """Host-side epoch-ms step grid as numpy int64.  Always numpy:
        epoch milliseconds overflow int32, and with jax_enable_x64 off a
        jnp array would silently truncate (device consumers rebase to
        small offsets before upload)."""
        import numpy as _np
        out = (_np.arange(self.num_steps, dtype=_np.int64) * self.step
               + _np.int64(self.start))
        return out if dtype is None else out.astype(dtype)


def window_bounds(ts: jnp.ndarray, steps: jnp.ndarray, window) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[S,R] sorted timestamps x [T] step ends -> (first, last) [S,T].

    ``first`` = index of first row with ts > step-window; ``last`` = index
    one past the last row with ts <= step.  Replaces the reference's
    per-window binarySearch/ceilingIndex (memory/format/vectors/
    LongBinaryVector.scala:152,162).
    """
    lo = steps - window
    R, T = ts.shape[1], steps.shape[0]
    from filodb_tpu.ops.grid import on_tpu_backend
    on_tpu = on_tpu_backend()
    if R * T <= 262_144 and on_tpu:
        # broadcast-compare-reduce: searchsorted(side='right') == count of
        # ts <= needle.  Pure VPU compare+reduce that XLA fuses without
        # materializing [S,R,T] — measured 12x faster than the bitonic-sort
        # lowering at [1M, 60] x 55 on v5e.  (XLA:CPU does materialize the
        # broadcast, so CPU always takes the searchsorted route below.)
        idx = jnp.int32
        first = (ts[:, :, None] <= lo[None, None, :]).sum(axis=1, dtype=idx)
        last = (ts[:, :, None] <= steps[None, None, :]).sum(axis=1, dtype=idx)
        return first, last
    # bitonic-sort lowering on TPU — no While loop in the HLO (the 'scan'
    # method emits lax.scan, which the TPU executes poorly and which
    # wedges the axon tunnel entirely); CPU takes the default lowering.
    method = "sort" if on_tpu else "scan"
    first = jax.vmap(lambda row: jnp.searchsorted(row, lo, side="right", method=method))(ts)
    last = jax.vmap(lambda row: jnp.searchsorted(row, steps, side="right", method=method))(ts)
    return first, last


def counter_correct(vals: jnp.ndarray) -> jnp.ndarray:
    """Prometheus counter-reset correction along the row axis.

    Wherever a value drops below its predecessor, all later values are
    shifted up by the predecessor — the running-prefix formulation of the
    reference's sequential CorrectionMeta threading
    (query/exec/rangefn/RangeFunction.scala:125-161).  ``vals`` is [S, R];
    correction runs along the row axis.
    """
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    drop = jnp.where((vals < prev), prev, 0.0)  # NaN comparisons are False
    return vals + jnp.cumsum(drop, axis=1)


def _prefix(x: jnp.ndarray) -> jnp.ndarray:
    """[S,R] -> [S,R+1] running sum with NaN treated as 0."""
    s = jnp.cumsum(jnp.where(jnp.isnan(x), 0.0, x), axis=1)
    return jnp.pad(s, ((0, 0), (1, 0)))


def _row_select(arr: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """arr [S,R], idx [S,T] in-range -> out[s,t] = arr[s, idx[s,t]].

    Formulated as a one-hot compare + masked reduce over R instead of
    ``take_along_axis``: TPU per-element gathers measured ~1.35s per [1M,55]
    pull vs ~90ms for the fused compare-reduce.  Falls back to gather for
    large R*T where the broadcast would dominate — and ALWAYS on non-TPU
    backends, where XLA:CPU materializes the [S,R,T] broadcast (measured
    ~100x slower than its native gathers).
    """
    R, T = arr.shape[1], idx.shape[1]
    from filodb_tpu.ops.grid import on_tpu_backend
    if R * T <= 262_144 and on_tpu_backend():
        rows = jnp.arange(R, dtype=idx.dtype)
        oh = rows[None, :, None] == idx[:, None, :]          # [S,R,T]
        return jnp.where(oh, arr[:, :, None], 0).sum(axis=1)
    return jnp.take_along_axis(arr, idx, axis=1)


def _at(P: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return _row_select(P, idx)


def _range_sum(P: jnp.ndarray, first: jnp.ndarray, last: jnp.ndarray) -> jnp.ndarray:
    return _at(P, last) - _at(P, first)


def _gather_rows(arr: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Per-series gather: arr [S,R], idx [S,T] (clipped) -> [S,T]."""
    return _row_select(arr, jnp.clip(idx, 0, arr.shape[1] - 1))


# --------------------------------------------------------------------------
# Prefix-path kernels
# --------------------------------------------------------------------------

def sum_count_avg(ts, vals, steps, window):
    """Returns (sum, count, avg) over each window in one pass."""
    first, last = window_bounds(ts, steps, window)
    s = _range_sum(_prefix(vals), first, last)
    n = _range_sum(_prefix(jnp.isfinite(vals).astype(vals.dtype)), first, last)
    empty = n == 0
    s = jnp.where(empty, jnp.nan, s)
    avg = jnp.where(empty, jnp.nan, s / jnp.where(empty, 1.0, n))
    return s, jnp.where(empty, jnp.nan, n), avg


def sum_over_time(ts, vals, steps, window):
    return sum_count_avg(ts, vals, steps, window)[0]


def count_over_time(ts, vals, steps, window):
    return sum_count_avg(ts, vals, steps, window)[1]


def avg_over_time(ts, vals, steps, window):
    return sum_count_avg(ts, vals, steps, window)[2]


def stdvar_stddev(ts, vals, steps, window):
    """Population variance/stddev via sum & sum-of-squares prefixes — the
    same moments the reference accumulates (AggrOverTimeFunctions.scala
    VarOverTimeChunkedFunctionD keeps sum & squaredSum), but centered on a
    per-series grand mean first so the E[x^2]-E[x]^2 cancellation cannot blow
    up (single-sample windows come out exactly 0, unlike the reference)."""
    first, last = window_bounds(ts, steps, window)
    fin = jnp.isfinite(vals)
    nrows = jnp.maximum(fin.sum(axis=1, keepdims=True), 1).astype(vals.dtype)
    center = jnp.where(fin, vals, 0.0).sum(axis=1, keepdims=True) / nrows
    x = vals - center
    s1 = _range_sum(_prefix(x), first, last)
    s2 = _range_sum(_prefix(x * x), first, last)
    n = _range_sum(_prefix(fin.astype(vals.dtype)), first, last)
    empty = n == 0
    nsafe = jnp.where(empty, 1.0, n)
    mean = s1 / nsafe
    var = jnp.maximum(s2 / nsafe - mean * mean, 0.0)
    var = jnp.where(empty, jnp.nan, var)
    return var, jnp.sqrt(var)


def stdvar_over_time(ts, vals, steps, window):
    return stdvar_stddev(ts, vals, steps, window)[0]


def stddev_over_time(ts, vals, steps, window):
    return stdvar_stddev(ts, vals, steps, window)[1]


def changes_over_time(ts, vals, steps, window):
    """Number of value changes between consecutive samples inside the window."""
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    chg = (vals != prev) & jnp.isfinite(vals) & jnp.isfinite(prev)
    first, last = window_bounds(ts, steps, window)
    C = _prefix(chg.astype(vals.dtype))
    # pair i covers rows (i-1, i); only pairs fully inside the window count
    raw = _at(C, last) - _at(C, jnp.minimum(first + 1, last))
    n = _range_sum(_prefix(jnp.isfinite(vals).astype(vals.dtype)), first, last)
    return jnp.where(n == 0, jnp.nan, raw)


def resets_over_time(ts, vals, steps, window):
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    rst = (vals < prev)
    first, last = window_bounds(ts, steps, window)
    C = _prefix(rst.astype(vals.dtype))
    raw = _at(C, last) - _at(C, jnp.minimum(first + 1, last))
    n = _range_sum(_prefix(jnp.isfinite(vals).astype(vals.dtype)), first, last)
    return jnp.where(n == 0, jnp.nan, raw)


def last_sample(ts, vals, steps, window):
    """Last *non-NaN* sample in the window and its timestamp: the raw-series
    instant selector (reference: LastSampleChunkedFunctionD,
    rangefn/RangeFunction.scala:408-542).  Returns (value, ts_ms) [S,T];
    ts_ms is -1 where no sample exists."""
    S, R = vals.shape
    rows = jnp.arange(R, dtype=jnp.int32)[None, :]
    lastfin = lax.cummax(jnp.where(jnp.isfinite(vals), rows, -1), axis=1)
    first, last = window_bounds(ts, steps, window)
    j = _gather_rows(lastfin, jnp.maximum(last - 1, 0))
    valid = (last > 0) & (j >= first) & (j >= 0)
    value = jnp.where(valid, _gather_rows(vals, j), jnp.nan)
    tstamp = jnp.where(valid, _gather_rows(ts, j), -1)
    return value, tstamp


def timestamp_fn(ts, vals, steps, window):
    """PromQL timestamp(): seconds of the last sample (reference
    rangefn/RangeFunction.scala:544 TimestampChunkedFunction).

    Precision note: this general path casts absolute epoch seconds to
    the value dtype — f32 on accelerators, which quantizes to ~128 s
    near current epochs.  The device-grid serving path is exact (the
    kernel emits window-relative seconds and the host re-bases in f64);
    only this fallback carries the rounding."""
    _, t = last_sample(ts, vals, steps, window)
    return jnp.where(t < 0, jnp.nan, t.astype(vals.dtype) / 1000.0)


# --------------------------------------------------------------------------
# Rate family
# --------------------------------------------------------------------------

def _extrapolated(delta, n, t1, t2, steps, window, v1, is_counter, is_rate, dtype):
    """Prometheus extrapolatedRate (reference RateFunctions.scala:37-80)."""
    wstart = (steps - window)[None, :].astype(dtype)  # exclusive start
    f = lambda x: x.astype(dtype)
    dur_start = (f(t1) - wstart) / 1000.0
    dur_end = (f(steps)[None, :] - f(t2)) / 1000.0
    sampled = (f(t2) - f(t1)) / 1000.0
    avg_dur = sampled / jnp.maximum(f(n) - 1.0, 1.0)
    if is_counter:
        dur_zero = sampled * v1 / jnp.where(delta == 0, 1.0, delta)
        clamp = (delta > 0) & (v1 >= 0) & (dur_zero < dur_start)
        dur_start = jnp.where(clamp, dur_zero, dur_start)
    thresh = avg_dur * 1.1
    extrap = (sampled
              + jnp.where(dur_start < thresh, dur_start, avg_dur / 2.0)
              + jnp.where(dur_end < thresh, dur_end, avg_dur / 2.0))
    scaled = delta * extrap / jnp.where(sampled == 0, 1.0, sampled)
    if is_rate:
        scaled = scaled / (jnp.asarray(window, dtype) / 1000.0)
    return jnp.where((n >= 2) & (sampled > 0), scaled, jnp.nan)


def _finite_bounds(ts, vals, steps, window):
    """Window bounds restricted to *finite* samples: (j1, j2, n_finite)
    [S,T] row indices of the first/last finite sample in each window and the
    finite count.  NaN rows are "no sample" (gauge gaps, padding) and must
    not act as rate/delta boundary samples."""
    first, last = window_bounds(ts, steps, window)
    fin = jnp.isfinite(vals)
    R = vals.shape[1]
    rows = jnp.arange(R, dtype=first.dtype)[None, :]
    lastfin = lax.cummax(jnp.where(fin, rows, -1), axis=1)
    nextfin = lax.cummin(jnp.where(fin, rows, R), axis=1, reverse=True)
    j2 = _gather_rows(lastfin, jnp.maximum(last - 1, 0))
    j1 = _gather_rows(nextfin, jnp.minimum(first, R - 1))
    n = _range_sum(_prefix(fin.astype(vals.dtype)), first, last)
    valid = (last > first) & (j2 >= j1) & (j1 < last) & (j2 >= 0) & (j1 < R)
    return jnp.where(valid, j1, 0), jnp.where(valid, j2, 0), jnp.where(valid, n, 0)


def _rate_family(ts, vals, steps, window, is_counter: bool, is_rate: bool):
    v = counter_correct(vals) if is_counter else vals
    j1, j2, n = _finite_bounds(ts, vals, steps, window)
    t1 = _gather_rows(ts, j1)
    t2 = _gather_rows(ts, j2)
    v1 = _gather_rows(v, j1)
    v2 = _gather_rows(v, j2)
    # for the counter zero-point clamp the reference uses window head value
    # post-correction (sliding) — corrected v1 is what we pass
    return _extrapolated(v2 - v1, n, t1, t2, steps, window, v1,
                         is_counter, is_rate, vals.dtype)


def rate(ts, vals, steps, window):
    return _rate_family(ts, vals, steps, window, is_counter=True, is_rate=True)


def increase(ts, vals, steps, window):
    return _rate_family(ts, vals, steps, window, is_counter=True, is_rate=False)


def delta_fn(ts, vals, steps, window):
    return _rate_family(ts, vals, steps, window, is_counter=False, is_rate=False)


def _instant_pair(ts, vals, steps, window, correct: bool):
    """Last two *finite* samples in the window (for irate/idelta)."""
    v = counter_correct(vals) if correct else vals
    fin = jnp.isfinite(vals)
    R = vals.shape[1]
    first, last = window_bounds(ts, steps, window)
    rows = jnp.arange(R, dtype=first.dtype)[None, :]
    lastfin = lax.cummax(jnp.where(fin, rows, -1), axis=1)
    j2 = _gather_rows(lastfin, jnp.maximum(last - 1, 0))
    j1 = _gather_rows(lastfin, jnp.maximum(j2 - 1, 0))
    valid = (last > first) & (j2 >= first) & (j2 > 0) & (j1 >= first) & (j1 >= 0) \
        & (j1 < j2)
    j1c, j2c = jnp.maximum(j1, 0), jnp.maximum(j2, 0)
    t1, t2 = _gather_rows(ts, j1c), _gather_rows(ts, j2c)
    v1, v2 = _gather_rows(v, j1c), _gather_rows(v, j2c)
    dt = (t2 - t1).astype(vals.dtype) / 1000.0
    return v1, v2, dt, valid


def irate(ts, vals, steps, window):
    """Instant rate from the last two samples (reference IRateFunction)."""
    v1, v2, dt, valid = _instant_pair(ts, vals, steps, window, correct=True)
    return jnp.where(valid & (dt > 0), (v2 - v1) / dt, jnp.nan)


def idelta(ts, vals, steps, window):
    # zero sampledInterval drops the pair, same as irate (the
    # reference's shared instant-pair guard; ADVICE r2)
    v1, v2, dt, valid = _instant_pair(ts, vals, steps, window, correct=False)
    return jnp.where(valid & (dt > 0), v2 - v1, jnp.nan)


# --------------------------------------------------------------------------
# Gather-path kernels
# --------------------------------------------------------------------------

def max_window_rows(ts, steps, window) -> int:
    """Host-side guard for the gather path: the exact max rows in any window.
    The engine calls this (cheap: one bounds pass) to pick a sufficient
    ``wmax`` bucket — gather_windows silently truncates windows wider than
    ``wmax``, so a too-small static bound must be caught here, not there."""
    first, last = window_bounds(jnp.asarray(ts), jnp.asarray(steps), window)
    return int(jnp.max(last - first))


def gather_windows(ts, vals, steps, window, wmax: int):
    """Materialize bounded per-window tiles: values [S,T,W] (NaN-masked) and
    x-offsets [S,T,W] in seconds relative to the step end (for regression
    kernels).  W = ``wmax`` must bound the max rows per window — see
    :func:`max_window_rows`; windows with more rows are silently truncated."""
    first, last = window_bounds(ts, steps, window)
    idx = first[:, :, None] + jnp.arange(wmax, dtype=first.dtype)[None, None, :]
    in_win = idx < last[:, :, None]
    cidx = jnp.clip(idx, 0, vals.shape[1] - 1)
    vw = jnp.take_along_axis(vals[:, None, :], cidx, axis=2)
    vw = jnp.where(in_win, vw, jnp.nan)
    tw = jnp.take_along_axis(ts[:, None, :], cidx, axis=2)
    xw = (tw - steps[None, :, None]).astype(vals.dtype) / 1000.0
    xw = jnp.where(in_win, xw, jnp.nan)
    return vw, xw


def min_over_time(ts, vals, steps, window, wmax: int):
    vw, _ = gather_windows(ts, vals, steps, window, wmax)
    return _nan_reduce(vw, jnp.min, jnp.inf)


def max_over_time(ts, vals, steps, window, wmax: int):
    vw, _ = gather_windows(ts, vals, steps, window, wmax)
    return _nan_reduce(vw, jnp.max, -jnp.inf)


def _nan_reduce(vw, op, identity):
    fin = jnp.isfinite(vw)
    out = op(jnp.where(fin, vw, identity), axis=-1)
    return jnp.where(fin.any(axis=-1), out, jnp.nan)


def quantile_over_time(ts, vals, steps, window, wmax: int, q: float):
    vw, _ = gather_windows(ts, vals, steps, window, wmax)
    if q > 1.0 or q < 0.0:
        # Prometheus returns ±Inf for out-of-range phi on windows that
        # have samples (reference QuantileOverTimeFunction), where
        # jnp.nanquantile would silently clamp; gather_windows pads
        # only with NaN, so presence = any non-NaN (±Inf samples count)
        live = (~jnp.isnan(vw)).any(axis=-1)
        return jnp.where(live, jnp.inf if q > 1.0 else -jnp.inf, jnp.nan)
    out = jnp.nanquantile(vw, q, axis=-1)
    return out


def mad_over_time(ts, vals, steps, window, wmax: int):
    """Median absolute deviation (reference MedianAbsoluteDeviationOverTime)."""
    vw, _ = gather_windows(ts, vals, steps, window, wmax)
    med = jnp.nanquantile(vw, 0.5, axis=-1)
    return jnp.nanquantile(jnp.abs(vw - med[..., None]), 0.5, axis=-1)


def _linreg(vw, xw):
    """Least-squares (slope, intercept-at-x=0) over the window tile; x is
    seconds relative to the step end (matches Prometheus linearRegression
    with interceptTime = range end)."""
    fin = jnp.isfinite(vw)
    n = fin.sum(axis=-1).astype(vw.dtype)
    x = jnp.where(fin, xw, 0.0)
    y = jnp.where(fin, vw, 0.0)
    sx, sy = x.sum(-1), y.sum(-1)
    sxx, sxy = (x * x).sum(-1), (x * y).sum(-1)
    nsafe = jnp.maximum(n, 1.0)
    cov = sxy - sx * sy / nsafe
    var = sxx - sx * sx / nsafe
    slope = cov / jnp.where(var == 0, 1.0, var)
    intercept = sy / nsafe - slope * (sx / nsafe)
    ok = (n >= 2) & (var > 0)
    return jnp.where(ok, slope, jnp.nan), jnp.where(ok, intercept, jnp.nan)


def deriv(ts, vals, steps, window, wmax: int):
    vw, xw = gather_windows(ts, vals, steps, window, wmax)
    return _linreg(vw, xw)[0]


def predict_linear(ts, vals, steps, window, wmax: int, duration_s: float):
    vw, xw = gather_windows(ts, vals, steps, window, wmax)
    slope, intercept = _linreg(vw, xw)
    return intercept + slope * duration_s


def z_score(ts, vals, steps, window):
    """(last - mean) / stddev over the window (reference ZScoreChunked).

    sd == 0 implies every sample equals the mean, so the exact numerator is
    0 and the result is NaN (0/0); prefix-sum rounding noise would otherwise
    turn it into spurious +/-inf."""
    lastv, _ = last_sample(ts, vals, steps, window)
    _, sd = stdvar_stddev(ts, vals, steps, window)
    _, n, mean = sum_count_avg(ts, vals, steps, window)
    # n < 2 implies sd is exactly 0 mathematically; prefix-sum rounding
    # can leave sd ~ 1e-9 and emit finite garbage without this guard
    return jnp.where((sd == 0) | ~(n >= 2), jnp.nan, (lastv - mean) / sd)


def holt_winters(ts, vals, steps, window, wmax: int, sf: float, tf: float):
    """Double exponential smoothing, Prometheus semantics: level seeded from
    the first sample, trend from the first pair, smoothed forward over the
    window (reference HoltWintersFunction, rangefn/AggrOverTimeFunctions)."""
    vw, _ = gather_windows(ts, vals, steps, window, wmax)  # [S,T,W]

    def step(carry, y):
        s, b, cnt = carry
        valid = jnp.isfinite(y)
        b_eff = jnp.where(cnt == 1, y - s, b)  # trend seeds from the first pair
        x = sf * y + (1 - sf) * (s + b_eff)
        s_new = jnp.where(cnt == 0, y, x)
        b_new = jnp.where(cnt == 0, 0.0, tf * (x - s) + (1 - tf) * b_eff)
        s_out = jnp.where(valid, s_new, s)
        b_out = jnp.where(valid, b_new, b)
        cnt_out = cnt + valid.astype(cnt.dtype)
        return (s_out, b_out, cnt_out), None

    S, T, W = vw.shape
    init = (jnp.zeros((S, T), vw.dtype), jnp.zeros((S, T), vw.dtype),
            jnp.zeros((S, T), jnp.int32))
    (s, b, cnt), _ = lax.scan(step, init, jnp.moveaxis(vw, -1, 0))
    return jnp.where(cnt >= 2, s, jnp.nan)
