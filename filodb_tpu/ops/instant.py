"""Instant (sample-wise) functions and binary operators.

Replaces the reference's InstantFunction family and ScalarOperationMapper
math (reference: query/exec/rangefn/InstantFunction.scala:81-110,
query/exec/rangefn/BinaryOperatorFunction.scala).  All are elementwise jnp
ops over ``[S, T]`` arrays — XLA fuses them into whatever kernel produced
the input, so they are effectively free on device.
"""

from __future__ import annotations

import jax.numpy as jnp


def _days_in_month(year, month):
    thirty_one = (month == 1) | (month == 3) | (month == 5) | (month == 7) | \
                 (month == 8) | (month == 10) | (month == 12)
    thirty = (month == 4) | (month == 6) | (month == 9) | (month == 11)
    leap = ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)
    return jnp.where(thirty_one, 31, jnp.where(thirty, 30, jnp.where(leap, 29, 28)))


def _civil_from_days(z):
    """days-since-epoch -> (year, month, day); Howard Hinnant's algorithm."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _ymd(v):
    secs = v.astype(jnp.int64) if v.dtype != jnp.int64 else v
    days = jnp.floor_divide(secs, 86400)
    return _civil_from_days(days)


INSTANT_FUNCTIONS = {}


def _register(name):
    def deco(fn):
        INSTANT_FUNCTIONS[name] = fn
        return fn
    return deco


@_register("abs")
def abs_(v):
    return jnp.abs(v)


@_register("ceil")
def ceil(v):
    return jnp.ceil(v)


@_register("floor")
def floor(v):
    return jnp.floor(v)


@_register("exp")
def exp(v):
    return jnp.exp(v)


@_register("ln")
def ln(v):
    return jnp.log(v)


@_register("log2")
def log2(v):
    return jnp.log2(v)


@_register("log10")
def log10(v):
    return jnp.log10(v)


@_register("sqrt")
def sqrt(v):
    return jnp.sqrt(v)


@_register("round")
def round_(v, to_nearest=1.0):
    # Prometheus round(): half away from... actually half rounds up
    return jnp.floor(v / to_nearest + 0.5) * to_nearest


@_register("clamp_max")
def clamp_max(v, mx):
    return jnp.minimum(v, mx)


@_register("clamp_min")
def clamp_min(v, mn):
    return jnp.maximum(v, mn)


@_register("sgn")
def sgn(v):
    return jnp.sign(v)


@_register("year")
def year(v):
    y, _, _ = _ymd(jnp.where(jnp.isnan(v), 0.0, v))
    return jnp.where(jnp.isnan(v), jnp.nan, y.astype(jnp.float64))


@_register("month")
def month(v):
    _, m, _ = _ymd(jnp.where(jnp.isnan(v), 0.0, v))
    return jnp.where(jnp.isnan(v), jnp.nan, m.astype(jnp.float64))


@_register("day_of_month")
def day_of_month(v):
    _, _, d = _ymd(jnp.where(jnp.isnan(v), 0.0, v))
    return jnp.where(jnp.isnan(v), jnp.nan, d.astype(jnp.float64))


@_register("day_of_week")
def day_of_week(v):
    secs = jnp.where(jnp.isnan(v), 0.0, v).astype(jnp.int64)
    days = jnp.floor_divide(secs, 86400)
    return jnp.where(jnp.isnan(v), jnp.nan, ((days + 4) % 7).astype(jnp.float64))


@_register("hour")
def hour(v):
    secs = jnp.where(jnp.isnan(v), 0.0, v).astype(jnp.int64)
    return jnp.where(jnp.isnan(v), jnp.nan, ((secs % 86400) // 3600).astype(jnp.float64))


@_register("minute")
def minute(v):
    secs = jnp.where(jnp.isnan(v), 0.0, v).astype(jnp.int64)
    return jnp.where(jnp.isnan(v), jnp.nan, ((secs % 3600) // 60).astype(jnp.float64))


@_register("days_in_month")
def days_in_month(v):
    y, m, _ = _ymd(jnp.where(jnp.isnan(v), 0.0, v))
    return jnp.where(jnp.isnan(v), jnp.nan, _days_in_month(y, m).astype(jnp.float64))


# --------------------------------------------------------------------------
# Binary operators (scalar-vector and vector-vector)
# --------------------------------------------------------------------------

BINARY_OPERATORS = {
    "ADD": jnp.add,
    "SUB": jnp.subtract,
    "MUL": jnp.multiply,
    "DIV": jnp.divide,
    "MOD": jnp.mod,
    "POW": jnp.power,
}

_COMPARISON = {
    "EQL": lambda a, b: a == b,
    "NEQ": lambda a, b: a != b,
    "GTR": lambda a, b: a > b,
    "LSS": lambda a, b: a < b,
    "GTE": lambda a, b: a >= b,
    "LTE": lambda a, b: a <= b,
}


def apply_binary(op: str, lhs, rhs, bool_mode: bool = False):
    """PromQL binary operator semantics: comparisons filter (keep lhs value)
    unless ``bool`` modifier, which yields 0/1 (reference
    BinaryOperatorFunction)."""
    if op in BINARY_OPERATORS:
        return BINARY_OPERATORS[op](lhs, rhs)
    if op.endswith("_BOOL"):
        op, bool_mode = op[:-5], True
    cmp = _COMPARISON[op](lhs, rhs)
    both = jnp.isfinite(lhs) if jnp.ndim(lhs) else jnp.ones_like(cmp, dtype=bool)
    if bool_mode:
        out = jnp.where(cmp, 1.0, 0.0)
        return jnp.where(jnp.isnan(lhs) | jnp.isnan(rhs), jnp.nan, out)
    return jnp.where(cmp & both, lhs, jnp.nan)
