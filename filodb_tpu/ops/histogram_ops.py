"""Histogram device kernels: per-bucket rate, quantile, bucket extraction.

Replaces the reference's histogram range functions and
HistogramQuantileMapper (reference: rangefn/RangeFunction.scala:376-377 hist
rate/increase, exec/HistogramQuantileMapper.scala:22, rangefn/
AggrOverTimeFunctions.scala SumOverTimeChunkedFunctionH).  Histogram batches
are dense ``[S, R, B]`` cumulative-bucket matrices; all bucket math is
vectorized over B.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from filodb_tpu.ops import windows as W


def _per_bucket(fn, ts, hist, *args):
    """vmap a scalar-series kernel over the bucket axis: hist [S,R,B]."""
    vb = jnp.moveaxis(hist, 2, 0)  # [B,S,R]
    out = jax.vmap(lambda v: fn(ts, v, *args))(vb)  # [B,S,T]
    return jnp.moveaxis(out, 0, 2)  # [S,T,B]


def hist_rate(ts, hist, steps, window):
    """Per-bucket Prometheus rate with counter correction (reference
    HistRateFunction)."""
    return _per_bucket(lambda t, v: W.rate(t, v, steps, window), ts, hist)


def hist_increase(ts, hist, steps, window):
    return _per_bucket(lambda t, v: W.increase(t, v, steps, window), ts, hist)


def hist_sum_over_time(ts, hist, steps, window):
    return _per_bucket(lambda t, v: W.sum_over_time(t, v, steps, window), ts, hist)


def hist_last_sample(ts, hist, steps, window):
    """Last histogram in window (instant selector for hist columns)."""
    return _per_bucket(lambda t, v: W.last_sample(t, v, steps, window)[0], ts, hist)


def hist_quantile(tops, hist, q):
    """histogram_quantile over dense bucket matrices [..., B] on device.

    Same interpolation contract as core.histogram.quantile_bulk (reference:
    memory/.../vectors/Histogram.scala:59-76): linear inside the located
    bucket, second-to-last top for the +Inf bucket, NaN for empty/NaN rows.
    """
    B = tops.shape[0]
    total = hist[..., -1]
    rank = q * total
    idx = jnp.sum(hist < rank[..., None], axis=-1)
    idx = jnp.minimum(idx, B - 1)
    count_at = jnp.take_along_axis(hist, idx[..., None], axis=-1)[..., 0]
    below_idx = jnp.maximum(idx - 1, 0)
    count_below = jnp.where(idx > 0,
                            jnp.take_along_axis(hist, below_idx[..., None], axis=-1)[..., 0],
                            0.0)
    top = tops[idx]
    bottom = jnp.where(idx > 0, tops[below_idx], 0.0)
    interp = bottom + (top - bottom) * (rank - count_below) / (count_at - count_below)
    out = jnp.where(idx == B - 1, tops[B - 2], interp)
    out = jnp.where((idx == 0) & (tops[0] <= 0), tops[0], out)
    out = jnp.where(jnp.isnan(total), jnp.nan, out)
    return jnp.where(q < 0, -jnp.inf, jnp.where(q > 1, jnp.inf, out))


def hist_max_quantile(tops, hist, maxes, q):
    """histogram_max_quantile: clamp to the observed max column (reference
    hist-max schema handling in MultiSchemaPartitionsExec)."""
    base = hist_quantile(tops, hist, q)
    return jnp.where(jnp.isfinite(maxes) & (base > maxes), maxes, base)


def hist_bucket(tops, hist, le):
    """histogram_bucket: extract one bucket as a plain series (reference
    InstantFunctionId.HistogramBucket)."""
    match = jnp.isclose(tops, le) | (jnp.isinf(tops) & jnp.isinf(jnp.asarray(le)))
    idx = jnp.argmax(match)
    found = match.any()
    return jnp.where(found, hist[..., idx], jnp.nan)
