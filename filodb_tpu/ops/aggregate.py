"""Cross-series aggregation kernels: segment reductions over group ids.

Replaces the reference's RowAggregator map/reduce family (reference:
query/exec/aggregator/RowAggregator.scala:29,114-141 — Sum/Min/Max/Count/
Avg/TopBottomK/Quantile/Stdvar/Stddev/CountValues) and the
``fastReduce`` fixed-window-array path (exec/AggrOverRangeVectors.scala:
151-277).  Grouping labels hash to segment ids on host
(:func:`group_ids`); reductions run on device and compose with ``psum``
over a mesh axis for cross-shard reduce (SURVEY.md §2.7 item 5).

All kernels take ``vals [S, T]`` (series x steps), ``ids [S]`` int32 and a
static ``num_groups`` and return ``[G, T]`` (or ``[G, k, T]`` for topk).
NaN entries do not contribute.
"""

from __future__ import annotations

from typing import Hashable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def group_ids(keys: Sequence[Hashable]) -> tuple[np.ndarray, list]:
    """Host-side: map per-series grouping keys to dense segment ids.

    Returns (ids [S] int32, unique keys in id order).  The unique keys become
    the result RangeVectorKeys (reference: by/without grouping in
    AggregateMapReduce, exec/AggrOverRangeVectors.scala:74-120).
    """
    index: dict[Hashable, int] = {}
    ids = np.empty(len(keys), dtype=np.int32)
    for i, k in enumerate(keys):
        ids[i] = index.setdefault(k, len(index))
    return ids, list(index.keys())


def _fin(vals):
    return jnp.isfinite(vals)


def _sum_count(vals, ids, num_groups: int):
    """(masked sum, finite count) — the shared core of sum/avg/count."""
    fin = _fin(vals)
    s = jax.ops.segment_sum(jnp.where(fin, vals, 0.0), ids, num_groups)
    n = jax.ops.segment_sum(fin.astype(vals.dtype), ids, num_groups)
    return s, n


def seg_sum(vals, ids, num_groups: int):
    s, n = _sum_count(vals, ids, num_groups)
    return jnp.where(n > 0, s, jnp.nan)


def seg_count(vals, ids, num_groups: int):
    _, n = _sum_count(vals, ids, num_groups)
    return jnp.where(n > 0, n, jnp.nan)


def seg_min(vals, ids, num_groups: int):
    m = jax.ops.segment_min(jnp.where(_fin(vals), vals, jnp.inf), ids, num_groups)
    return jnp.where(jnp.isfinite(m), m, jnp.nan)


def seg_max(vals, ids, num_groups: int):
    m = jax.ops.segment_max(jnp.where(_fin(vals), vals, -jnp.inf), ids, num_groups)
    return jnp.where(jnp.isfinite(m), m, jnp.nan)


def seg_avg(vals, ids, num_groups: int):
    return seg_mean_count(vals, ids, num_groups)[0]


def seg_mean_count(vals, ids, num_groups: int):
    """(mean, count) pair — the mergeable state the reference's AvgAggregator
    carries across shards (mean+count columns)."""
    s, n = _sum_count(vals, ids, num_groups)
    return jnp.where(n > 0, s / jnp.maximum(n, 1.0), jnp.nan), n


def seg_stdvar(vals, ids, num_groups: int):
    fin = _fin(vals)
    s1 = jax.ops.segment_sum(jnp.where(fin, vals, 0.0), ids, num_groups)
    s2 = jax.ops.segment_sum(jnp.where(fin, vals * vals, 0.0), ids, num_groups)
    n = jax.ops.segment_sum(fin.astype(vals.dtype), ids, num_groups)
    nsafe = jnp.maximum(n, 1.0)
    mean = s1 / nsafe
    var = jnp.maximum(s2 / nsafe - mean * mean, 0.0)
    return jnp.where(n > 0, var, jnp.nan)


def seg_stddev(vals, ids, num_groups: int):
    return jnp.sqrt(seg_stdvar(vals, ids, num_groups))


def seg_topk(vals, ids, num_groups: int, k: int, bottom: bool = False,
             max_group_size: int | None = None):
    """Per-group per-step top/bottom-k (reference TopBottomKAggregator).

    Returns (values [G,k,T], series_index [G,k,T] int32; index -1 / NaN value
    where the group has fewer than k live series at that step).

    Formulation: scatter series into a dense ``[G, M, T]`` cube by
    position-within-group (computed in-graph via a stable argsort + running
    count), then a single ``lax.top_k`` over the member axis.  ``M`` defaults
    to S; pass ``max_group_size`` to shrink the cube when group sizes are
    known on host.
    """
    S, T = vals.shape
    M = S if max_group_size is None else max_group_size
    order = jnp.argsort(ids, stable=True)
    sids = ids[order]
    arange_s = jnp.arange(S, dtype=jnp.int32)
    newg = jnp.concatenate([jnp.ones((1,), bool), sids[1:] != sids[:-1]])
    gstart = jax.lax.cummax(jnp.where(newg, arange_s, 0))
    pos = arange_s - gstart                      # position within group
    sentinel = -jnp.inf
    sign = -1.0 if bottom else 1.0
    dense = jnp.full((num_groups, M, T), sentinel, vals.dtype)
    svals = jnp.where(_fin(vals), vals, jnp.nan)[order] * sign
    dense = dense.at[sids, pos].set(jnp.where(jnp.isnan(svals), sentinel, svals))
    smap = jnp.full((num_groups, M), -1, jnp.int32).at[sids, pos].set(
        order.astype(jnp.int32))
    work = jnp.moveaxis(dense, 1, 2)             # [G, T, M]
    keff = min(k, M)
    topv, topm = jax.lax.top_k(work, keff)       # [G, T, keff]
    if keff < k:  # pad out to the requested k with empty slots
        pad = ((0, 0), (0, 0), (0, k - keff))
        topv = jnp.pad(topv, pad, constant_values=-jnp.inf)
        topm = jnp.pad(topm, pad, constant_values=0)
    found = jnp.isfinite(topv)
    topsi = jnp.take_along_axis(smap[:, None, :], topm, axis=2)
    values = jnp.where(found, topv * sign, jnp.nan)
    indices = jnp.where(found, topsi, -1)
    return jnp.moveaxis(values, 1, 2), jnp.moveaxis(indices, 1, 2)  # [G,k,T]


def seg_quantile(vals, ids, num_groups: int, q: float):
    """Exact per-group quantile via a masked [G,S,T] expansion.  The engine
    enforces the reference's group-by cardinality limit (filodb-defaults
    ``group-by-cardinality-limit`` = 1000) so G stays bounded; the reference
    itself approximates with t-digest (QuantileAggregator) — exact here."""
    S, T = vals.shape
    mask = ids[None, :] == jnp.arange(num_groups, dtype=ids.dtype)[:, None]  # [G,S]
    expanded = jnp.where(mask[:, :, None], vals[None, :, :], jnp.nan)
    return jnp.nanquantile(expanded, q, axis=1)


def absent(vals):
    """1.0 at steps where no series has a value (reference AbsentFunctionMapper)."""
    any_present = jnp.isfinite(vals).any(axis=0)
    return jnp.where(any_present, jnp.nan, 1.0)


def seg_hist_sum(hist, ids, num_groups: int):
    """Sum histograms bucket-wise: hist [S,T,B] -> [G,T,B] (reference
    HistSumAggregator; bucket-schema mismatch handled upstream)."""
    fin = jnp.isfinite(hist)
    s = jax.ops.segment_sum(jnp.where(fin, hist, 0.0), ids, num_groups)
    n = jax.ops.segment_sum(fin.astype(hist.dtype), ids, num_groups)
    return jnp.where(n > 0, s, jnp.nan)
