"""Device-side (jax) t-digest construction for mesh quantile partials.

The host t-digest (query/tdigest.py) is the batched numpy form the
aggregation layer merges and presents; this module is its jax twin so
the SPMD mesh program can SKETCH ON DEVICE: each device digests its
local shards' windowed values ([S, T] -> [G, T, C] centroids), the
digests ride one all_gather, and a final on-device compress folds the
per-device sketches — only O(G*T*C) crosses the host link no matter the
series cardinality (reference: QuantileRowAggregator's TDigest partial
rows, query/src/main/scala/filodb/query/exec/aggregator/
RowAggregator.scala:114-141).

Same k1 scale function and binning as the numpy implementation, so
device-built digests merge losslessly with host-built ones in
QuantileAggregator.reduce.
"""

from __future__ import annotations

import numpy as np


def compress(means, weights, compression: int):
    """Compress [..., N] centroid sets to C slots (jax twin of
    tdigest._compress).  NaN means / zero weights are empty slots."""
    import jax.numpy as jnp

    order = jnp.argsort(means, axis=-1)            # NaNs sort last
    m = jnp.take_along_axis(means, order, axis=-1)
    w = jnp.take_along_axis(weights, order, axis=-1)
    w = jnp.where(jnp.isfinite(m), w, 0.0)
    total = w.sum(axis=-1, keepdims=True)
    cumw = jnp.cumsum(w, axis=-1)
    qmid = jnp.where(total > 0,
                     (cumw - w / 2.0) / jnp.maximum(total, 1e-300), 0.0)
    q = jnp.clip(qmid, 0.0, 1.0)
    kval = compression / np.pi * (jnp.arcsin(2.0 * q - 1.0) + np.pi / 2.0)
    kidx = jnp.clip(kval.astype(jnp.int32), 0, compression - 1)
    lead = means.shape[:-1]
    out_shape = (*lead, compression)
    # scatter-add centroids into their k-bins, all cells at once
    idx = tuple(jnp.arange(n).reshape(
        *([1] * i), n, *([1] * (len(lead) - i)))
        for i, n in enumerate(lead))
    wm = w * jnp.where(jnp.isfinite(m), m, 0.0)
    w_out = jnp.zeros(out_shape, w.dtype).at[(*idx, kidx)].add(w)
    wm_out = jnp.zeros(out_shape, w.dtype).at[(*idx, kidx)].add(wm)
    m_out = jnp.where(w_out > 0, wm_out / jnp.maximum(w_out, 1e-300),
                      jnp.nan)
    return m_out, w_out


def digest_from_series(vals, ids, num_groups: int, compression: int):
    """Per-(group, step) digests from windowed series values on device.

    ``vals`` [S, T] (NaN = no sample), ``ids`` [S] group per series
    (out-of-range ids land in a dropped spare group).  Processes series
    in slabs of C under ``lax.scan`` so peak memory is O(G*T*2C)
    regardless of S (jax twin of tdigest.from_values, which documents
    the same slab invariant).  Returns (means, weights) [G, T, C]."""
    import jax.numpy as jnp
    from jax import lax

    S, T = vals.shape
    G1 = num_groups + 1                            # + drop group
    C = compression
    slab = C
    nslab = max(-(-S // slab), 1)
    Sp = nslab * slab
    vpad = jnp.pad(vals, ((0, Sp - S), (0, 0)), constant_values=jnp.nan)
    ipad = jnp.clip(jnp.pad(ids, (0, Sp - S),
                            constant_values=num_groups), 0, num_groups)
    vs = vpad.reshape(nslab, slab, T)
    gs = ipad.reshape(nslab, slab)
    m0 = jnp.full((G1, T, C), jnp.nan, vals.dtype)
    w0 = jnp.zeros((G1, T, C), vals.dtype)
    jj = jnp.arange(slab)

    def body(carry, xs):
        m, w = carry
        sv, sid = xs                               # [slab, T], [slab]
        # series j of the slab owns member slot j of its group
        mem_m = jnp.full((G1, T, slab), jnp.nan,
                         vals.dtype).at[sid, :, jj].set(sv)
        mem_w = jnp.zeros((G1, T, slab), vals.dtype).at[sid, :, jj].set(
            jnp.isfinite(sv).astype(vals.dtype))
        m2, w2 = compress(jnp.concatenate([m, mem_m], axis=-1),
                          jnp.concatenate([w, mem_w], axis=-1), C)
        return (m2, w2), None

    (m, w), _ = lax.scan(body, (m0, w0), (vs, gs))
    return m[:num_groups], w[:num_groups]
