"""Tenant SLO burn-rate tracker (ISSUE 19 pillar 2).

Declarative objectives — per tenant / priority class, a latency
threshold plus an availability target — tracked as multi-window
multi-burn-rate counters (the SRE-workbook shape: a fast window that
pages on budget-torching incidents, a slow window that warns on
sustained leaks).

Exported as ``filodb_slo_*`` families.  The burn rates are LEVEL
gauges on purpose (the ``filodb_ingest_stalled`` lesson: a counter's
label set is born at 1, so a rules-engine ``increase()`` never sees
the 0->1 edge); the self-monitoring rule pack's SLO extension
(rules/selfmon.slo_pack) alerts on ``filodb_slo_fast_burn`` /
``filodb_slo_slow_burn`` through the normal inactive -> pending ->
firing machine.

Snapshots are mergeable like the workload ledger's: integer totals per
objective (thresholds echoed as ints — ms and ppm — so config echoes
compare exactly across nodes).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from filodb_tpu.utils.observability import slo_metrics


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declarative objective.  ``tenant``/``priority`` are exact
    matches with ``*`` as the wildcard; ``target`` is the availability
    fraction (0.999 = 0.1% error budget); a request is GOOD when it
    neither errored nor exceeded ``latency_threshold_s``."""

    name: str
    tenant: str = "*"
    priority: str = "*"
    latency_threshold_s: float = 1.0
    target: float = 0.999

    @staticmethod
    def from_config(conf: dict, index: int = 0) -> "SloObjective":
        return SloObjective(
            name=str(conf.get("name", f"slo-{index}")),
            tenant=str(conf.get("tenant", "*")),
            priority=str(conf.get("priority", "*")),
            latency_threshold_s=float(
                conf.get("latency-threshold-s", 1.0)),
            target=float(conf.get("availability-target", 0.999)))

    def matches(self, tenant: str, priority: str) -> bool:
        return (self.tenant in ("*", tenant)
                and self.priority in ("*", priority))

    def budget(self) -> float:
        """Error budget = 1 - target, floored so target=1.0 does not
        divide by zero (burn saturates instead)."""
        return max(1.0 - self.target, 1e-9)


class _Window:
    """One objective's per-second ring of (total, bad) counts; burn
    rates read the last N seconds.  Bounded by the slow window size."""

    def __init__(self, max_age_s: float):
        self.max_age_s = float(max_age_s)
        self._ring: collections.deque = collections.deque()

    def observe(self, bad: bool, now_s: float) -> None:
        sec = int(now_s)
        if self._ring and self._ring[-1][0] == sec:
            t, tot, b = self._ring[-1]
            self._ring[-1] = (t, tot + 1, b + (1 if bad else 0))
        else:
            self._ring.append((sec, 1, 1 if bad else 0))
        horizon = now_s - self.max_age_s
        while self._ring and self._ring[0][0] < horizon:
            self._ring.popleft()

    def counts(self, window_s: float, now_s: float) -> tuple[int, int]:
        horizon = now_s - window_s
        tot = bad = 0
        for sec, t, b in self._ring:
            if sec >= horizon:
                tot += t
                bad += b
        return tot, bad


class SloTracker:
    """Per-node tracker: observe every query outcome, export level
    burn-rate gauges, answer mergeable snapshots."""

    def __init__(self, objectives: list[SloObjective], node: str = "",
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0):
        self.node = node
        self.objectives = list(objectives)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        # totals + rings live under _lock; gauge set_fn callbacks
        # re-take it briefly at scrape time (never under a metric lock)
        self._lock = threading.Lock()
        self._state: dict[str, dict] = {  # guarded-by: _lock
            o.name: {"total": 0, "bad": 0,
                     "window": _Window(max(slow_window_s, fast_window_s))}
            for o in self.objectives}
        self._m = slo_metrics()
        for o in self.objectives:
            labels = {"objective": o.name, "tenant": o.tenant,
                      "node": self.node}
            # LEVEL gauges registered up front: the row exists at 0
            # before the first breach, so the rules engine sees the
            # full 0 -> burning edge (counters-born-at-1 lesson)
            self._m["fast_burn"].set_fn(
                (lambda _o=o: self.burn(_o.name, self.fast_window_s)),
                **labels)
            self._m["slow_burn"].set_fn(
                (lambda _o=o: self.burn(_o.name, self.slow_window_s)),
                **labels)
            self._m["budget"].set(o.budget(), **labels)

    # -------------------------------------------------------------- writes

    def observe(self, tenant: str, priority: str, latency_s: float,
                error: bool = False) -> None:
        now_s = time.time()
        for o in self.objectives:
            if not o.matches(tenant, priority):
                continue
            bad = error or latency_s > o.latency_threshold_s
            labels = {"objective": o.name, "tenant": o.tenant,
                      "node": self.node}
            with self._lock:
                st = self._state[o.name]
                st["total"] += 1
                if bad:
                    st["bad"] += 1
                st["window"].observe(bad, now_s)
            self._m["requests"].inc(**labels)
            if bad:
                self._m["breaches"].inc(**labels)

    # --------------------------------------------------------------- reads

    def burn(self, objective: str, window_s: float) -> float:
        """Burn rate over the window: (bad fraction) / (error budget).
        1.0 = exactly consuming budget at the sustainable rate; the
        fast-burn page threshold is conventionally 14.4 (2% of a 30-day
        budget in one hour)."""
        obj = next((o for o in self.objectives if o.name == objective),
                   None)
        if obj is None:
            return 0.0
        now_s = time.time()
        with self._lock:
            tot, bad = self._state[objective]["window"].counts(window_s,
                                                               now_s)
        if tot == 0:
            return 0.0
        return (bad / tot) / obj.budget()

    def snapshot(self) -> dict:
        """Mergeable per-node snapshot: integer totals per objective +
        the objective config echoed as ints (ms / ppm) so identical
        configs compare exactly across nodes."""
        now_s = time.time()
        out: dict = {"node": self.node,
                     "fast_window_s": self.fast_window_s,
                     "slow_window_s": self.slow_window_s,
                     "objectives": {}}
        with self._lock:
            for o in self.objectives:
                st = self._state[o.name]
                ftot, fbad = st["window"].counts(self.fast_window_s,
                                                 now_s)
                stot, sbad = st["window"].counts(self.slow_window_s,
                                                 now_s)
                out["objectives"][o.name] = {
                    "tenant": o.tenant, "priority": o.priority,
                    "latency_threshold_ms":
                        int(round(o.latency_threshold_s * 1000)),
                    "target_ppm": int(round(o.target * 1_000_000)),
                    "total": st["total"], "bad": st["bad"],
                    "fast": {"total": ftot, "bad": fbad},
                    "slow": {"total": stot, "bad": sbad}}
        return out

    def rows(self) -> list[dict]:
        """The human-facing per-objective rollup for /admin/insights."""
        snap = self.snapshot()
        rows = []
        for name, st in sorted(snap["objectives"].items()):
            rows.append({
                "objective": name, "tenant": st["tenant"],
                "priority": st["priority"],
                "latency_threshold_ms": st["latency_threshold_ms"],
                "target": st["target_ppm"] / 1e6,
                "total": st["total"], "bad": st["bad"],
                "fast_burn": round(self.burn(name, self.fast_window_s),
                                   4),
                "slow_burn": round(self.burn(name, self.slow_window_s),
                                   4)})
        return rows

    def close(self) -> None:
        """Drop this node's exported gauge rows (the Gauge.remove
        contract): a dead node's burn rates must not feed the
        self-monitoring alerts forever."""
        for o in self.objectives:
            labels = {"objective": o.name, "tenant": o.tenant,
                      "node": self.node}
            self._m["fast_burn"].remove(**labels)
            self._m["slow_burn"].remove(**labels)
            self._m["budget"].remove(**labels)


def merge_slo(snaps: list[dict]) -> dict:
    """Exact merge of per-node SLO snapshots: integer totals sum;
    objective configs must agree (they come from one cluster config —
    a mismatch is surfaced, not averaged away)."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return {"nodes": [], "objectives": {}}
    out: dict = {"nodes": [], "objectives": {}}
    for s in snaps:
        out["nodes"].extend(s.get("nodes") or
                            ([s["node"]] if s.get("node") else []))
        for name, st in s.get("objectives", {}).items():
            cur = out["objectives"].get(name)
            if cur is None:
                out["objectives"][name] = {
                    **st, "fast": dict(st["fast"]),
                    "slow": dict(st["slow"])}
                continue
            for k in ("tenant", "priority", "latency_threshold_ms",
                      "target_ppm"):
                if cur[k] != st[k]:
                    cur[f"{k}_mismatch"] = True
            cur["total"] += st["total"]
            cur["bad"] += st["bad"]
            for w in ("fast", "slow"):
                cur[w]["total"] += st[w]["total"]
                cur[w]["bad"] += st[w]["bad"]
    out["nodes"] = sorted(set(out["nodes"]))
    out["objectives"] = {k: out["objectives"][k]
                         for k in sorted(out["objectives"])}
    return out
