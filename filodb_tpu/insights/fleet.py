"""Fleet console aggregator (ISSUE 19 pillar 3).

A FleetAggregator on every coordinator polls its cluster peers' raw
mergeable snapshots (``/admin/insights?raw=true``, the same membership
view the StatusPoller gossips over) and serves one merged
``/admin/fleet`` tree: the fleet workload ledger, SLO counters,
watermark-lag totals, per-node replica health, and the kernel
flight-deck summaries — the one-pane view that previously required
curl-ing N nodes and merging JSON by hand.

Unreachable peers never fail the view: their row is marked with the
snapshot age (staleness) and the error, and their LAST known snapshot
keeps contributing until it expires.
"""

from __future__ import annotations

import concurrent.futures
import json
import threading
import time
import urllib.request

from filodb_tpu.insights import ledger as _ledger
from filodb_tpu.insights import slo as _slo
from filodb_tpu.utils.observability import (PeriodicThread,
                                            insights_metrics)


class FleetAggregator:
    """Poll peers' raw bundles; merge on read (tree()).

    ``interval_s > 0`` enables BACKGROUND polling (opt-in: a console
    must never add steady cross-node chatter to a cluster nobody is
    looking at — chaos/partition tests especially must not see extra
    peer traffic they didn't script).  ``interval_s <= 0`` is the
    on-demand mode: no thread, every ``tree()`` read does one
    synchronous poll round, so /admin/fleet is always fresh and a
    quiet cluster sees zero fleet traffic."""

    def __init__(self, node: str, peers: dict, local_fn,
                 interval_s: float = 0.0, timeout_s: float = 2.0,
                 stale_after_s: float = 60.0):
        self.node = node
        self.peers = {n: ep for n, ep in (peers or {}).items()
                      if n != node}
        self.local_fn = local_fn
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.stale_after_s = float(stale_after_s)
        # _lock covers the per-peer result cache ONLY; peer fetches
        # always run outside it (a wedged peer must not block
        # /admin/fleet readers or the next poll round)
        self._lock = threading.Lock()
        self._cache: dict[str, dict] = {}  # guarded-by: _lock
        self._m = insights_metrics()
        self._thread = None
        if self.interval_s > 0:
            self._thread = PeriodicThread(self.poll, self.interval_s,
                                          name=f"fleet-{node}")

    def start(self) -> None:
        if self._thread is not None and self.peers:
            self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._thread.stop()

    # -------------------------------------------------------------- polling

    def _fetch(self, endpoint: str) -> dict:
        url = f"{endpoint}/admin/insights?raw=true"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            body = json.loads(resp.read())
        data = body.get("data")
        if not isinstance(data, dict):
            raise ValueError(f"malformed insights payload from {url}")
        return data

    def poll(self) -> None:
        """One synchronous poll round over every peer (also the
        ``?refresh=true`` path).  Fetches run concurrently and OUTSIDE
        the cache lock; results land under it."""
        if not self.peers:
            return
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(len(self.peers), 8),
                thread_name_prefix=f"fleet-{self.node}") as pool:
            futs = {pool.submit(self._fetch, ep): peer
                    for peer, ep in self.peers.items()}
            for fut in concurrent.futures.as_completed(futs):
                peer = futs[fut]
                try:
                    bundle = fut.result()
                except Exception as e:  # noqa: BLE001 — peer down/slow
                    self._m["fleet_polls"].inc(peer=peer,
                                               outcome="error")
                    with self._lock:
                        row = self._cache.setdefault(peer, {})
                        row["error"] = repr(e)[:200]
                    continue
                self._m["fleet_polls"].inc(peer=peer, outcome="ok")
                with self._lock:
                    self._cache[peer] = {"bundle": bundle,
                                         "fetched_s": time.time(),
                                         "error": None}

    # ---------------------------------------------------------------- reads

    def tree(self, refresh: bool = False) -> dict:
        """The merged fleet view.  ``refresh=True`` forces a
        synchronous poll round first (tests + operator curl); in
        on-demand mode (no background thread) every read polls, so the
        console is never staler than the last curl."""
        if refresh or self._thread is None:
            self.poll()
        now = time.time()
        local = self.local_fn()
        bundles = [local]
        nodes = {self.node: {"ok": True, "stale_s": 0.0, "error": None,
                             "local": True}}
        with self._lock:
            cache = {p: dict(r) for p, r in self._cache.items()}
        for peer in sorted(self.peers):
            row = cache.get(peer)
            if row is None or "bundle" not in row:
                nodes[peer] = {"ok": False, "stale_s": None,
                               "error": (row or {}).get("error")
                               or "not yet polled", "local": False}
                continue
            age = now - row["fetched_s"]
            ok = row.get("error") is None and age <= self.stale_after_s
            nodes[peer] = {"ok": ok, "stale_s": round(age, 3),
                           "error": row.get("error"), "local": False}
            if age <= self.stale_after_s:
                bundles.append(row["bundle"])
        insights = _ledger.merge_snapshots(
            [b.get("insights") for b in bundles])
        slo = _slo.merge_slo([b["slo"] for b in bundles
                              if b.get("slo")])
        watermarks: dict = {}
        for b in bundles:
            for ds, tot in (b.get("watermarks") or {}).items():
                row = watermarks.get(ds)
                if row is None:
                    watermarks[ds] = dict(tot)
                else:
                    for k, v in tot.items():
                        if isinstance(v, (int, float)):
                            row[k] = row.get(k, 0) + v
        replicas = {b.get("node", "?"): b.get("replicas")
                    for b in bundles if b.get("replicas") is not None}
        kernels = {b.get("node", "?"): b.get("kernels")
                   for b in bundles if b.get("kernels") is not None}
        return {"node": self.node, "nodes": nodes,
                "insights": insights, "slo": slo,
                "watermarks": watermarks, "replicas": replicas,
                "kernels": kernels}
