"""Per-fingerprint workload ledger (ISSUE 19 pillar 1).

A bounded per-coordinator table keyed by the canonical-PromQL plan
fingerprint (query/resultcache.plan_fingerprint), accumulating the
per-query observations the serving path already carries on
QueryStats/ExecContext: count, a mergeable fixed-bucket latency
histogram, samples scanned, result-cache hit/partial/miss, sampled
device programs + HBM bytes, admission sheds and deadline refusals.

Each fingerprint also carries a **batch-compatibility key**
``dataset|plan-family|resolution|grid-steps``: queries sharing one key
could have run as ONE vmapped launch (the DrJAX vmap-over-clients
idiom, arXiv:2403.07128).  A sliding co-arrival window per batch key
measures how many queries actually arrive close enough together to
batch — the empirical headroom number ROADMAP item 2 (fleet-scale
multi-query batching) needs before anyone writes the batching tier.

Merge algebra: every accumulator is an integer (latency sums are
microseconds, never float seconds) and the histogram bounds are the
module constant below, so merging node snapshots is EXACT — sums of
ints and max of peaks are commutative, associative, and invariant to
how the query stream was partitioned across nodes
(tests/test_insights.py proves all three generatively).
"""

from __future__ import annotations

import bisect
import collections
import threading
import time

# Fixed latency bucket bounds (milliseconds).  A MODULE CONSTANT on
# purpose: every node buckets with the same bounds, so elementwise
# summing per-node bucket counts is an exact histogram merge.  Changing
# these invalidates cross-version fleet merges — bump with care.
LATENCY_BUCKETS_MS = (1, 2, 5, 10, 25, 50, 100, 250, 500,
                      1000, 2500, 5000, 10000, 30000)

# co-arrival window entries kept per batch key (newest win); bounds the
# deque a hot key can grow even if the window knob is cranked up
_MAX_ARRIVALS = 4096


def plan_keys(dataset: str, plan, query: str) -> tuple[str, str]:
    """(fingerprint, batch_key) for one query's logical plan.

    The fingerprint is the result cache's canonical rendering when the
    shape supports one; non-fingerprintable shapes fall back to the raw
    query text + step so they are still attributed (prefixed ``q:`` to
    keep the namespaces disjoint).  The batch key folds what a vmapped
    multi-query launch must share: dataset, plan family (root logical
    op), resolution, and the step-grid size.
    """
    from filodb_tpu.query import logical as lp
    from filodb_tpu.query.resultcache import plan_fingerprint
    try:
        start, step, end = lp.time_range(plan)
    except (ValueError, TypeError):
        family = type(plan).__name__
        return (f"q:{family}:{query[:200]}",
                f"{dataset}|{family}|res=0|steps=0")
    fp = None
    try:
        # instant queries carry step=0; the fingerprint's phase term
        # divides by step, so treat them as non-cacheable shapes
        fp = plan_fingerprint(plan, step, start) if step > 0 else None
    except (ValueError, TypeError, ZeroDivisionError):
        fp = None
    if fp is None:
        fp = f"q:{query[:200]}|step={step}"
    steps = (end - start) // step + 1 if step > 0 else 1
    family = type(plan).__name__
    return fp, f"{dataset}|{family}|res={step}|steps={steps}"


def _new_batch_row() -> dict:
    """One batch-key row: the co-arrival headroom estimate (arrivals /
    co_arrived / peak, fed by note_arrival) next to the REALIZED
    batching achieved by the fleet batching tier (batched_groups /
    batched_members / realized_peak, fed by note_batch)."""
    return {"arrivals": 0, "co_arrived": 0, "peak": 1,
            "batched_groups": 0, "batched_members": 0,
            "realized_peak": 0}


def _new_entry(query: str, dataset: str, batch_key: str) -> dict:
    return {"query": query, "dataset": dataset, "batch_key": batch_key,
            "count": 0, "errors": 0, "latency_us": 0,
            "lat_buckets": [0] * (len(LATENCY_BUCKETS_MS) + 1),
            "samples": 0, "rc_hit": 0, "rc_partial": 0, "rc_miss": 0,
            "device_programs": 0, "device_us": 0, "hbm_bytes": 0,
            "sheds": {}, "tenants": {}}


class WorkloadLedger:
    """One node's bounded fingerprint table + co-arrival tracker."""

    def __init__(self, node: str = "", max_entries: int = 512,
                 co_window_ms: float = 250.0, enabled: bool = True):
        self.node = node
        self.max_entries = int(max_entries)
        self.co_window_ms = float(co_window_ms)
        self.enabled = enabled
        self.started_at_ms = int(time.time() * 1000)
        # the whole table lives under _lock: note() does pure dict
        # arithmetic under it, never I/O or metric callbacks
        self._fps = collections.OrderedDict()  # guarded-by: _lock
        self._batch: dict[str, dict] = {}  # guarded-by: _lock
        self._arrivals: dict[str, collections.deque] = {}  # guarded-by: _lock
        self._tenants: dict[str, dict] = {}  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    # -------------------------------------------------------------- writes

    def note_arrival(self, batch_key: str) -> int:
        """Record one query arriving for ``batch_key``; returns how many
        same-key queries (this one included) arrived within the sliding
        co-arrival window — the size of the vmapped launch they could
        have shared.  Called at materialize time, before execution."""
        if not self.enabled:
            return 1
        now = time.monotonic()
        horizon = now - self.co_window_ms / 1000.0
        with self._lock:
            dq = self._arrivals.get(batch_key)
            if dq is None:
                dq = self._arrivals[batch_key] = collections.deque(
                    maxlen=_MAX_ARRIVALS)
                # bound the arrival-tracker key space like the table
                while len(self._arrivals) > self.max_entries:
                    self._arrivals.pop(next(iter(self._arrivals)))
            while dq and dq[0] < horizon:
                dq.popleft()
            dq.append(now)
            co = len(dq)
            row = self._batch.get(batch_key)
            if row is None:
                row = self._batch[batch_key] = _new_batch_row()
                while len(self._batch) > self.max_entries:
                    self._batch.pop(next(iter(self._batch)))
            row["arrivals"] += 1
            if co > 1:
                row["co_arrived"] += 1
            if co > row["peak"]:
                row["peak"] = co
            return co

    def note_batch(self, batch_key: str, size: int) -> None:
        """Record one REALIZED vmapped batch of ``size`` members for
        ``batch_key`` (ISSUE 20: the batching tier closes the headroom
        loop — achieved group sizes land next to the co-arrival
        estimate, so operators see predicted vs realized batching per
        key)."""
        if not self.enabled or not batch_key or size <= 0:
            return
        with self._lock:
            row = self._batch.get(batch_key)
            if row is None:
                row = self._batch[batch_key] = _new_batch_row()
                while len(self._batch) > self.max_entries:
                    self._batch.pop(next(iter(self._batch)))
            row["batched_groups"] += 1
            row["batched_members"] += int(size)
            if size > row["realized_peak"]:
                row["realized_peak"] = int(size)

    def note(self, fingerprint: str, *, query: str = "", dataset: str = "",
             tenant: str = "", latency_s: float = 0.0, error: bool = False,
             samples: int = 0, resultcache: str = "",
             device_programs: int = 0, device_s: float = 0.0,
             hbm_bytes: int = 0, shed_reason: str = "",
             batch_key: str = "") -> int:
        """Fold one completed (or shed/failed) query into the table.
        Returns how many LRU entries this call evicted (the caller
        feeds the ``filodb_insights_dropped_total`` counter)."""
        if not self.enabled or not fingerprint:
            return 0
        lat_ms = latency_s * 1000.0
        bucket = bisect.bisect_left(LATENCY_BUCKETS_MS, lat_ms)
        lat_us = int(round(latency_s * 1e6))
        dev_us = int(round(device_s * 1e6))
        evicted = 0
        with self._lock:
            e = self._fps.get(fingerprint)
            if e is None:
                e = self._fps[fingerprint] = _new_entry(query, dataset,
                                                       batch_key)
                while len(self._fps) > self.max_entries:
                    self._fps.popitem(last=False)
                    self._dropped += 1
                    evicted += 1
            else:
                self._fps.move_to_end(fingerprint)
                # witness fields fold by max() — the SAME algebra
                # merge_snapshots uses, so one ledger accumulating the
                # whole stream equals any partitioned merge exactly
                for k, v in (("query", query), ("dataset", dataset),
                             ("batch_key", batch_key)):
                    if v > e[k]:
                        e[k] = v
            e["count"] += 1
            e["lat_buckets"][bucket] += 1
            e["latency_us"] += lat_us
            e["samples"] += int(samples)
            if error:
                e["errors"] += 1
            if resultcache:
                e[f"rc_{resultcache}"] = e.get(f"rc_{resultcache}", 0) + 1
            e["device_programs"] += int(device_programs)
            e["device_us"] += dev_us
            e["hbm_bytes"] += int(hbm_bytes)
            if shed_reason:
                e["sheds"][shed_reason] = \
                    e["sheds"].get(shed_reason, 0) + 1
            if tenant:
                e["tenants"][tenant] = e["tenants"].get(tenant, 0) + 1
            t = self._tenants.get(tenant or "")
            if t is None:
                t = self._tenants[tenant or ""] = {
                    "count": 0, "errors": 0, "latency_us": 0, "samples": 0}
            t["count"] += 1
            t["latency_us"] += lat_us
            t["samples"] += int(samples)
            if error:
                t["errors"] += 1
        return evicted

    # --------------------------------------------------------------- reads

    def snapshot(self) -> dict:
        """The mergeable per-node snapshot: integers + fixed bounds
        only, no wall-clock-derived values (repeated snapshots of a
        quiesced ledger are bit-identical, which the fleet-merge
        exactness test depends on)."""
        with self._lock:
            return {
                "node": self.node,
                "bounds_ms": list(LATENCY_BUCKETS_MS),
                "started_at_ms": self.started_at_ms,
                "dropped": self._dropped,
                "fingerprints": {
                    k: {**v, "lat_buckets": list(v["lat_buckets"]),
                        "sheds": dict(v["sheds"]),
                        "tenants": dict(v["tenants"])}
                    for k, v in self._fps.items()},
                "batch": {k: dict(v) for k, v in self._batch.items()},
                "tenants": {k: dict(v) for k, v in self._tenants.items()},
            }

    def fingerprints(self) -> int:
        with self._lock:
            return len(self._fps)


# ---------------------------------------------------------------------------
# merge algebra
# ---------------------------------------------------------------------------


def _merge_entry(a: dict, b: dict) -> dict:
    out = dict(a)
    # string witnesses merge by max(): deterministic, commutative, and
    # associative even if two nodes saw different example renderings
    for k in ("query", "dataset", "batch_key"):
        out[k] = max(a.get(k, ""), b.get(k, ""))
    for k in ("count", "errors", "latency_us", "samples", "rc_hit",
              "rc_partial", "rc_miss", "device_programs", "device_us",
              "hbm_bytes"):
        out[k] = a.get(k, 0) + b.get(k, 0)
    out["lat_buckets"] = [x + y for x, y in zip(a["lat_buckets"],
                                                b["lat_buckets"])]
    out["sheds"] = dict(a.get("sheds", {}))
    for k, v in b.get("sheds", {}).items():
        out["sheds"][k] = out["sheds"].get(k, 0) + v
    out["tenants"] = dict(a.get("tenants", {}))
    for k, v in b.get("tenants", {}).items():
        out["tenants"][k] = out["tenants"].get(k, 0) + v
    return out


def merge_snapshots(snaps: list[dict]) -> dict:
    """Exact merge of per-node ledger snapshots into one fleet view.
    Commutative + associative + partition-invariant; bucket bounds must
    match (they are a module constant, so a mismatch means mixed
    software versions — refused rather than silently mis-merged)."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return {"nodes": [], "bounds_ms": list(LATENCY_BUCKETS_MS),
                "started_at_ms": 0, "dropped": 0, "fingerprints": {},
                "batch": {}, "tenants": {}}
    bounds = snaps[0].get("bounds_ms", list(LATENCY_BUCKETS_MS))
    for s in snaps[1:]:
        if s.get("bounds_ms", bounds) != bounds:
            raise ValueError("cannot merge snapshots with different "
                             "latency bucket bounds (mixed versions?)")
    nodes: list[str] = []
    fps: dict[str, dict] = {}
    batch: dict[str, dict] = {}
    tenants: dict[str, dict] = {}
    dropped = 0
    started = []
    for s in snaps:
        nodes.extend(s.get("nodes") or
                     ([s["node"]] if s.get("node") else []))
        dropped += int(s.get("dropped", 0))
        if s.get("started_at_ms"):
            started.append(int(s["started_at_ms"]))
        for k, v in s.get("fingerprints", {}).items():
            fps[k] = _merge_entry(fps[k], v) if k in fps else \
                {**v, "lat_buckets": list(v["lat_buckets"]),
                 "sheds": dict(v.get("sheds", {})),
                 "tenants": dict(v.get("tenants", {}))}
        for k, v in s.get("batch", {}).items():
            row = batch.get(k)
            if row is None:
                # normalize through the full row shape so realized
                # fields merged from OLD snapshots (pre-ISSUE 20)
                # default to 0 and the algebra stays exact
                batch[k] = {**_new_batch_row(), **v}
            else:
                row["arrivals"] += v.get("arrivals", 0)
                row["co_arrived"] += v.get("co_arrived", 0)
                row["peak"] = max(row["peak"], v.get("peak", 1))
                row["batched_groups"] += v.get("batched_groups", 0)
                row["batched_members"] += v.get("batched_members", 0)
                row["realized_peak"] = max(row["realized_peak"],
                                           v.get("realized_peak", 0))
        for k, v in s.get("tenants", {}).items():
            row = tenants.get(k)
            if row is None:
                tenants[k] = dict(v)
            else:
                for f in ("count", "errors", "latency_us", "samples"):
                    row[f] += v.get(f, 0)
    return {"nodes": sorted(set(nodes)), "bounds_ms": list(bounds),
            "started_at_ms": min(started) if started else 0,
            "dropped": dropped,
            "fingerprints": {k: fps[k] for k in sorted(fps)},
            "batch": {k: batch[k] for k in sorted(batch)},
            "tenants": {k: tenants[k] for k in sorted(tenants)}}


# ---------------------------------------------------------------------------
# derived views (/admin/insights, /admin/fleet, cli insights)
# ---------------------------------------------------------------------------


def _quantile_ms(entry: dict, q: float) -> float:
    """Bucket-interpolated latency quantile (ms) from the fixed-bound
    histogram — the usual Prometheus histogram_quantile estimate."""
    total = entry["count"]
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0
    lo = 0.0
    for i, hi in enumerate(LATENCY_BUCKETS_MS):
        n = entry["lat_buckets"][i]
        if cum + n >= target and n > 0:
            return lo + (hi - lo) * (target - cum) / n
        cum += n
        lo = float(hi)
    return float(LATENCY_BUCKETS_MS[-1])


def _cost(entry: dict) -> int:
    """One scalar "cost" rank: scan volume + device time + HBM traffic
    (unit-less; only used to order the top-k view)."""
    return (entry["samples"] + entry["device_us"]
            + entry["hbm_bytes"] // 1024)


def view(snapshot: dict, top: int = 20, sort: str = "cost") -> dict:
    """The human-facing rollup of a (per-node or merged) snapshot:
    top-k fingerprints by cost/latency/qps, the per-tenant rollup, and
    the batching-headroom table."""
    fps = snapshot.get("fingerprints", {})
    window_s = 0.0
    if snapshot.get("started_at_ms"):
        window_s = max(time.time() - snapshot["started_at_ms"] / 1000.0,
                       1e-3)
    keyfns = {
        "cost": _cost,
        "latency": lambda e: e["latency_us"],
        "count": lambda e: e["count"],
        "qps": lambda e: e["count"],
        "errors": lambda e: e["errors"],
    }
    keyfn = keyfns.get(sort, _cost)
    rows = []
    for fp, e in sorted(fps.items(), key=lambda kv: (-keyfn(kv[1]),
                                                     kv[0]))[:top]:
        rows.append({
            "fingerprint": fp, "query": e["query"],
            "dataset": e["dataset"], "batch_key": e["batch_key"],
            "count": e["count"], "errors": e["errors"],
            "qps": round(e["count"] / window_s, 4) if window_s else 0.0,
            "avg_ms": round(e["latency_us"] / 1000.0 / e["count"], 3)
            if e["count"] else 0.0,
            "p50_ms": round(_quantile_ms(e, 0.50), 3),
            "p95_ms": round(_quantile_ms(e, 0.95), 3),
            "p99_ms": round(_quantile_ms(e, 0.99), 3),
            "samples": e["samples"],
            "resultcache": {"hit": e["rc_hit"], "partial": e["rc_partial"],
                            "miss": e["rc_miss"]},
            "device_programs": e["device_programs"],
            "device_ms": round(e["device_us"] / 1000.0, 3),
            "hbm_bytes": e["hbm_bytes"], "sheds": dict(e["sheds"]),
            "tenants": dict(e["tenants"])})
    batch_rows = []
    for k, v in sorted(snapshot.get("batch", {}).items(),
                       key=lambda kv: (-kv[1]["peak"], kv[0]))[:top]:
        batch_rows.append({"batch_key": k, **_new_batch_row(), **v})
    batch_vals = snapshot.get("batch", {}).values()
    headroom = max((v["peak"] for v in batch_vals), default=0)
    realized_peak = max((v.get("realized_peak", 0)
                         for v in batch_vals), default=0)
    realized_groups = sum(v.get("batched_groups", 0)
                          for v in batch_vals)
    realized_members = sum(v.get("batched_members", 0)
                           for v in batch_vals)
    return {"nodes": snapshot.get("nodes") or
            ([snapshot["node"]] if snapshot.get("node") else []),
            "window_s": round(window_s, 3),
            "fingerprints": len(fps),
            "dropped": snapshot.get("dropped", 0),
            "sort": sort if sort in keyfns else "cost",
            "top": rows,
            "tenants": snapshot.get("tenants", {}),
            "batching": {"headroom": headroom,
                         "realized_peak": realized_peak,
                         "realized_groups": realized_groups,
                         "realized_members": realized_members,
                         "keys": batch_rows}}
