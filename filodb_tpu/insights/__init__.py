"""Fleet workload insights (ISSUE 19, doc/observability.md).

Three pillars over the existing serving path:

- ``ledger``: the per-coordinator workload ledger — a bounded table
  keyed by the canonical-PromQL plan fingerprint (query/resultcache.py)
  accumulating per-query observations the exec path already carries,
  plus the batch-compatibility co-arrival window that measures the
  empirical vmap-batching headroom (ROADMAP item 2);
- ``slo``: declarative per-tenant/priority SLO objectives tracked as
  multi-window burn rates, exported as ``filodb_slo_*`` level gauges
  the self-monitoring rule pack alerts on;
- ``fleet``: the FleetAggregator polling cluster peers' raw snapshots
  into one merged ``/admin/fleet`` tree.

Every snapshot here is MERGEABLE: integer accumulators and fixed
module-constant histogram bounds, so merging per-node snapshots is
exact (commutative, associative, partition-invariant — the PR 9
ledger-reconciliation discipline, proven by tests/test_insights.py).
"""

from filodb_tpu.insights.fleet import FleetAggregator
from filodb_tpu.insights.ledger import (LATENCY_BUCKETS_MS, WorkloadLedger,
                                        merge_snapshots, plan_keys)
from filodb_tpu.insights.slo import SloObjective, SloTracker, merge_slo

__all__ = ["FleetAggregator", "LATENCY_BUCKETS_MS", "SloObjective",
           "SloTracker", "WorkloadLedger", "merge_slo", "merge_snapshots",
           "plan_keys"]
