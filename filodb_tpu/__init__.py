"""FiloDB-TPU: a TPU-native, distributed, Prometheus-compatible time-series database.

A from-scratch rebuild of the capabilities of FiloDB (reference: Scala/JVM,
/root/reference) designed TPU-first:

- Columnar chunks live as padded dense device arrays ``[series, rows]``;
  the leaf scan -> window -> aggregate query hot path runs as jitted XLA
  (and Pallas) kernels using prefix-sum window formulations instead of the
  reference's per-row iterator loops (reference:
  query/exec/PeriodicSamplesMapper.scala, query/exec/rangefn/RangeFunction.scala).
- Sharding maps onto a ``jax.sharding.Mesh`` axis; cross-shard aggregation
  rides XLA collectives (psum) instead of Akka scatter-gather
  (reference: coordinator/ActorPlanDispatcher).
- Host code keeps planning, tag indexing, ingestion bookkeeping, and
  persistence — mirroring the reference's layer map (SURVEY.md §1).
"""

__version__ = "0.1.0"
