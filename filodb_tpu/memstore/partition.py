"""Per-series partition state: write buffers + frozen chunks.

Equivalent of the reference's TimeSeriesPartition (reference:
core/src/main/scala/filodb.core/memstore/TimeSeriesPartition.scala:64):
appends land in pre-allocated write buffers; when full (or at flush
boundaries) ``switch_buffers`` freezes them into a compressed ``ChunkSet``
(the encodeOneChunkset step, :203-249); out-of-order samples are dropped
(:131-134).  Queries read through ``read_range`` which serves decoded dense
arrays — the device-facing form.
"""

from __future__ import annotations

import logging
import struct
import threading
from typing import NamedTuple, Optional, Sequence

import numpy as np

from filodb_tpu import integrity
from filodb_tpu.codecs import histcodec
from filodb_tpu.core.chunk import ChunkSet, decode_chunkset, encode_chunkset
from filodb_tpu.core.histogram import HistogramBuckets
from filodb_tpu.core.schemas import ColumnType, Schema

_EMPTY_I64 = np.empty(0, dtype=np.int64)


class PendingBuffer(NamedTuple):
    """A detached-but-not-yet-encoded write buffer.  ``freeze_raw`` (the
    ingest thread's half of a flush) produces these in O(1); the flush
    executor encodes them into ChunkSets later (reference: prepareFlushGroup
    switchBuffers on the ingest thread, encode in doFlushSteps on the flush
    scheduler — TimeSeriesShard.scala:756-774, 884-974)."""

    ts: np.ndarray
    cols: list
    hist_buckets: Optional[HistogramBuckets]
    seq: int


class TimeSeriesPartition:
    __slots__ = ("part_id", "schema", "partkey", "tags", "group",
                 "chunks", "_decoded", "_buf_ts", "_buf_cols", "_buf_n",
                 "_capacity", "_hist_buckets", "_seq", "_unflushed",
                 "_pending", "_lock", "_encode_lock",
                 "out_of_order_dropped", "on_freeze", "on_corrupt")

    def __init__(self, part_id: int, schema: Schema, partkey: bytes,
                 tags: dict[str, str], group: int, capacity: int = 400):
        self.part_id = part_id
        self.schema = schema
        self.partkey = partkey
        self.tags = tags
        self.group = group
        self.chunks: list[ChunkSet] = []
        self._decoded: dict[int, tuple] = {}   # chunk_id -> (ts, cols)
        self._capacity = capacity
        # write buffers allocate lazily on first ingest: paged-in /
        # snapshot partitions never ingest, and the ODP cold path
        # constructs thousands of them per query
        self._buf_ts = _EMPTY_I64
        self._buf_cols: Optional[list] = None
        self._buf_n = 0
        self._hist_buckets: Optional[HistogramBuckets] = None
        self._seq = 0
        self._unflushed: list[ChunkSet] = []
        # raw frozen buffers awaiting encode (pipelined flush); guarded by
        # _lock together with chunks/_unflushed so flush-executor encodes
        # never interleave badly with ingest freezes or query reads
        self._pending: list[PendingBuffer] = []
        self._lock = threading.Lock()
        # serializes whole drain_pending runs (ingest thread's buffer-full
        # encode vs a flush-executor encode of the same partition); taken
        # OUTSIDE the buffer lock (enforced by filolint):
        # lock-order: _encode_lock < TimeSeriesPartition._lock
        self._encode_lock = threading.Lock()
        self.out_of_order_dropped = 0
        # shard hook observing chunk freezes (device grid invalidation)
        self.on_freeze = None
        # shard hook observing corrupt-chunk detections: (err, newly) ->
        # None, bumps shard stats (set wherever partitions are built)
        self.on_corrupt = None

    def _new_col_buffer(self, ctype: ColumnType):
        if ctype == ColumnType.DOUBLE:
            return np.empty(self._capacity, dtype=np.float64)
        if ctype in (ColumnType.LONG, ColumnType.TIMESTAMP, ColumnType.INT):
            return np.empty(self._capacity, dtype=np.int64)
        return []  # STRING / HISTOGRAM: python list, frozen at encode time

    def _alloc_buffers_locked(self) -> None:
        self._buf_ts = np.empty(self._capacity, dtype=np.int64)
        self._buf_cols = [self._new_col_buffer(c.ctype)
                          for c in self.schema.data.columns[1:]]

    # -- ingest -------------------------------------------------------------

    def ingest(self, timestamp: int, values: Sequence) -> bool:
        """Append one sample.  Returns False for out-of-order drops.

        All buffer mutation happens under ``_lock`` so an off-thread
        flush (``flush_now``/admin ``flush_all``) freezing this buffer
        concurrently cannot interleave with a half-written row; encoding
        of anything frozen here is deferred until after the lock drops
        (lock order: never hold ``_lock`` while taking ``_encode_lock``).
        """
        if timestamp <= self.latest_timestamp:
            self.out_of_order_dropped += 1
            return False
        # decode histogram blobs first: a bucket-scheme switch mid-stream
        # freezes the current buffer (reference: AddResponse.
        # BucketSchemaMismatch forces a new vector, BinaryVector.scala:231-236)
        decoded = []
        new_buckets = None
        for col, v in zip(self.schema.data.columns[1:], values):
            if col.ctype == ColumnType.HISTOGRAM:
                buckets, counts = histcodec.decode_hist_value(v) \
                    if isinstance(v, (bytes, bytearray)) else v
                new_buckets = buckets
                decoded.append(np.asarray(counts, dtype=np.int64))
            else:
                decoded.append(v)
        froze = False
        with self._lock:
            if self._buf_cols is None:
                self._alloc_buffers_locked()
            if new_buckets is not None:
                if self._hist_buckets is not None and self._buf_n > 0 \
                        and new_buckets != self._hist_buckets:
                    froze = self._freeze_raw_locked() or froze
                self._hist_buckets = new_buckets
            if self._buf_n == self._capacity:
                froze = self._freeze_raw_locked() or froze
            i = self._buf_n
            self._buf_ts[i] = timestamp
            for buf, col, v in zip(self._buf_cols,
                                   self.schema.data.columns[1:], decoded):
                if col.ctype in (ColumnType.HISTOGRAM, ColumnType.STRING):
                    buf.append(v)
                else:
                    buf[i] = v
            self._buf_n = i + 1
        if froze:
            self.drain_pending()
        return True

    def ingest_block(self, ts: np.ndarray, cols: Sequence
                     ) -> tuple[int, int]:
        """Append a block of samples (the C++ columnar decode path).
        Scalar columns are numpy arrays; a histogram column is a
        ``(HistogramBuckets, int64[rows, nb])`` pair covering the whole
        block under ONE scheme (the shard splits mixed runs).
        Vectorized out-of-order drop: a sample survives iff it exceeds
        every timestamp before it in (chunks + block) — identical to
        per-record ``ingest`` because dropped samples never advance the
        high-water mark.  Returns (rows_added, rows_dropped)."""
        n = len(ts)
        if n == 0:
            return 0, 0
        new_buckets = None
        for c in cols:
            if isinstance(c, tuple):
                new_buckets = c[0]
        froze = False
        with self._lock:
            # high-water mark inline (the property would re-take _lock)
            if self._buf_n:
                lt = int(self._buf_ts[self._buf_n - 1])
            elif self._pending:
                lt = int(self._pending[-1].ts[-1])
            elif self.chunks:
                lt = self.chunks[-1].info.end_time
            else:
                lt = -1
            running = np.maximum.accumulate(np.concatenate(([lt], ts)))[:-1]
            keep = ts > running
            kept = int(keep.sum())
            dropped = n - kept
            self.out_of_order_dropped += dropped
            if kept == 0:
                return 0, dropped
            if kept != n:
                ts = ts[keep]
                cols = [(c[0], c[1][keep]) if isinstance(c, tuple)
                        else c[keep] for c in cols]
            # bucket-scheme switch freezes the current buffer, same as
            # the per-record path (reference: BucketSchemaMismatch).
            # This runs AFTER the out-of-order drop: a fully-dropped
            # block must not freeze anything or move the scheme, exactly
            # like per-record ingest() returns before scheme handling.
            if new_buckets is not None:
                if self._hist_buckets is not None and self._buf_n > 0 \
                        and new_buckets != self._hist_buckets:
                    froze = self._freeze_raw_locked() or froze
                self._hist_buckets = new_buckets
            if self._buf_cols is None:
                self._alloc_buffers_locked()
            i = 0
            while i < kept:
                if self._buf_n == self._capacity:
                    froze = self._freeze_raw_locked() or froze
                take = min(self._capacity - self._buf_n, kept - i)
                j = self._buf_n
                self._buf_ts[j:j + take] = ts[i:i + take]
                for buf, arr in zip(self._buf_cols, cols):
                    if isinstance(arr, tuple):
                        # hist buffer is a list of per-row count arrays;
                        # list slice assignment extends it in place.
                        # .copy() bounds retention to the buffered rows —
                        # views would pin the whole container matrix
                        # until this buffer freezes
                        buf[j:j + take] = list(arr[1][i:i + take].copy())
                    else:
                        buf[j:j + take] = arr[i:i + take]
                self._buf_n = j + take
                i += take
        if froze:
            # encode outside _lock (lock order: _encode_lock then _lock)
            self.drain_pending()
        return kept, dropped

    @property
    def latest_timestamp(self) -> int:
        with self._lock:
            if self._buf_n:
                return int(self._buf_ts[self._buf_n - 1])
            if self._pending:
                return int(self._pending[-1].ts[-1])
            if self.chunks:
                return self.chunks[-1].info.end_time
            return -1

    @property
    def earliest_timestamp(self) -> int:
        with self._lock:
            if self.chunks:
                return self.chunks[0].info.start_time
            if self._pending:
                return int(self._pending[0].ts[0])
            if self._buf_n:
                return int(self._buf_ts[0])
            return -1

    @property
    def num_chunks(self) -> int:
        return len(self.chunks) + len(self._pending) + (1 if self._buf_n else 0)

    def mutable_floor(self) -> Optional[int]:
        """Earliest MUTABLE (write-buffer / pending-encode) row
        timestamp, or None when everything is encoded — the result
        cache's closed-segment probe (query/resultcache.py): a result
        computed over an interval the mutable region reaches could
        still change without the encoded chunk set changing (encoded
        chunks themselves are immutable, so the shard's chunk-span
        table IS the digest of everything else)."""
        with self._lock:
            mt: Optional[int] = None
            if self._pending:
                mt = int(self._pending[0].ts[0])
            if self._buf_n:
                bt = int(self._buf_ts[0])
                mt = bt if mt is None or bt < mt else mt
            return mt

    def freeze_raw(self) -> bool:
        """Detach the current write buffer as a PendingBuffer in O(1) —
        the ingest-thread half of a pipelined flush (reference:
        prepareFlushGroup/switchBuffers, TimeSeriesShard.scala:756-774).
        Encoding happens later in :meth:`drain_pending` on the flush
        executor.  Returns True if anything froze."""
        with self._lock:
            return self._freeze_raw_locked()

    def _freeze_raw_locked(self) -> bool:
        n = self._buf_n
        if n == 0:
            return False
        cols = [buf[:n] for buf in self._buf_cols]
        self._pending.append(PendingBuffer(self._buf_ts[:n], cols,
                                           self._hist_buckets, self._seq))
        self._seq += 1
        self._buf_n = 0
        self._alloc_buffers_locked()
        return True

    def drain_pending(self) -> list[ChunkSet]:
        """Encode all pending buffers into ChunkSets, in seq order.  Safe
        from the flush executor: encoding runs outside the lock; the
        append-to-chunks + unpend step is atomic under the lock so query
        reads never see a sample twice or not at all."""
        out: list[ChunkSet] = []
        with self._encode_lock:
            out.extend(self._drain_pending_locked())
        return out

    def _drain_pending_locked(self) -> list[ChunkSet]:
        out: list[ChunkSet] = []
        while True:
            with self._lock:
                if not self._pending:
                    break
                pb = self._pending[0]
            cols = []
            for buf, col in zip(pb.cols, self.schema.data.columns[1:]):
                if col.ctype == ColumnType.HISTOGRAM:
                    cols.append((pb.hist_buckets, np.stack(list(buf))))
                elif col.ctype == ColumnType.STRING:
                    cols.append(list(buf))
                else:
                    cols.append(np.asarray(buf))
            cs = encode_chunkset(self.schema, self.partkey, pb.ts, cols,
                                 ingestion_seq=pb.seq)
            with self._lock:
                self.chunks.append(cs)
                self._unflushed.append(cs)
                self._pending.pop(0)
            if self.on_freeze is not None:
                self.on_freeze(cs)
            out.append(cs)
        return out

    def switch_buffers(self) -> Optional[ChunkSet]:
        """Freeze the current write buffer into a compressed ChunkSet
        (reference: switchBuffers + encodeOneChunkset).  Synchronous:
        freeze + encode in one call."""
        had = self.freeze_raw()
        encoded = self.drain_pending()
        return encoded[-1] if had and encoded else None

    def make_flush_chunks(self) -> list[ChunkSet]:
        """Freeze + drain chunks not yet persisted (reference:
        makeFlushChunks, TimeSeriesPartition.scala:264).  Single-thread
        use (ingest thread / batch jobs); the pipelined flush executor
        calls :meth:`collect_flush_chunks` instead, which does NOT
        freeze — the ingest thread already froze at prepare time."""
        self.freeze_raw()
        return self.collect_flush_chunks()

    def collect_flush_chunks(self) -> list[ChunkSet]:
        """Encode already-frozen pending buffers and drain the unflushed
        list.  Never touches the live write buffer, so it is safe from
        the flush executor while the ingest thread keeps appending."""
        self.drain_pending()
        with self._lock:
            out, self._unflushed = self._unflushed, []
        return out

    def requeue_unflushed(self, chunksets: Sequence[ChunkSet]) -> None:
        """Put collected-but-not-persisted chunksets back at the head of
        the unflushed list (a failed store write must not lose them —
        the next flush retries; writes are idempotent by chunk id)."""
        with self._lock:
            self._unflushed = list(chunksets) + self._unflushed

    # -- read ---------------------------------------------------------------

    def _decoded_chunk(self, cs: ChunkSet) -> tuple:
        got = self._decoded.get(cs.info.chunk_id)
        if got is None:
            try:
                got = decode_chunkset(self.schema, cs)
            except integrity.CorruptVectorError:
                raise
            except (ValueError, IndexError, struct.error) as e:
                # every native/numpy decode -1 sentinel surfaces here as
                # ValueError (IndexError/struct.error for truncated
                # frames): re-raise STRUCTURED, with part-key, chunk id,
                # the failing codec and a bounded hexdump window
                raise integrity.corrupt_chunk_error(cs, e) from e
            self._decoded[cs.info.chunk_id] = got
        return got

    def _note_corrupt(self, err: "integrity.CorruptVectorError") -> None:
        """Funnel a detected corrupt chunk: quarantine + counters (once
        per chunk), then the shard hook for per-shard stats."""
        new = integrity.report_corrupt(err)
        if self.on_corrupt is not None:
            self.on_corrupt(err, new)

    def drop_decoded_cache(self) -> None:
        self._decoded.clear()

    def read_range(self, start: int, end: int, column_id: Optional[int] = None):
        """All samples with start <= ts <= end as dense arrays.

        Returns (ts[int64], values) where values is float64 for scalar
        columns or (HistogramBuckets, int64[rows, buckets]) for histograms.
        Replaces per-row VectorDataReader iteration with whole-chunk decode +
        concatenation; the windowing kernels do the range math on device.
        """
        cid = self.schema.data.value_column_id if column_id is None else column_id
        col_idx = cid - 1  # data columns after the timestamp
        ctype = self.schema.data.columns[cid].ctype
        # one locked snapshot of chunks + pending + write-buffer tail:
        # freeze_raw moves the buffer into pending under the same lock, so
        # a concurrent reader sees each sample in exactly one of the three
        with self._lock:
            chunks_snap = list(self.chunks)
            pending_snap = list(self._pending)
            buf_n = self._buf_n
            buf_ts = self._buf_ts
            buf_cols = self._buf_cols
            buf_hist = self._hist_buckets
        ts_parts, val_parts = [], []
        # quarantined chunks are excluded from serving: the scan returns
        # partial data (flagged upstream), never values that failed a
        # checksum or decode
        q_ids = integrity.QUARANTINE.chunk_ids(self.partkey) \
            if integrity.QUARANTINE else ()
        for cs in chunks_snap:
            if cs.info.end_time < start or cs.info.start_time > end:
                continue
            if q_ids and cs.info.chunk_id in q_ids:
                continue
            try:
                ts, cols = self._decoded_chunk(cs)
                vals = cols[col_idx]   # truncated frame: missing column
            except integrity.CorruptVectorError as err:
                self._note_corrupt(err)   # quarantine + count, serve rest
                continue
            except IndexError:
                self._note_corrupt(integrity.corrupt_chunk_error(
                    cs, f"column {col_idx + 1} missing from decoded "
                        f"chunk"))
                continue
            ts_parts.append(ts)
            val_parts.append(vals)
        for pb in pending_snap:
            if int(pb.ts[-1]) < start or int(pb.ts[0]) > end:
                continue
            ts_parts.append(np.asarray(pb.ts))
            buf = pb.cols[col_idx]
            if ctype == ColumnType.HISTOGRAM:
                val_parts.append((pb.hist_buckets, np.stack(list(buf))))
            elif ctype == ColumnType.STRING:
                val_parts.append(list(buf))
            else:
                val_parts.append(np.asarray(buf, dtype=np.float64))
        if buf_n:
            t0 = int(buf_ts[0])
            if not (buf_ts[buf_n - 1] < start or t0 > end):
                ts_parts.append(buf_ts[:buf_n].copy())
                buf = buf_cols[col_idx]
                if ctype == ColumnType.HISTOGRAM:
                    val_parts.append((buf_hist, np.stack(buf[:buf_n])))
                elif ctype == ColumnType.STRING:
                    val_parts.append(list(buf[:buf_n]))
                else:
                    val_parts.append(buf[:buf_n].copy())
        if not ts_parts:
            empty_ts = np.empty(0, dtype=np.int64)
            if ctype == ColumnType.HISTOGRAM:
                return empty_ts, (self._hist_buckets, np.empty((0, 0), dtype=np.int64))
            return empty_ts, np.empty(0, dtype=np.float64)
        ts = ts_parts[0] if len(ts_parts) == 1 \
            else np.concatenate(ts_parts)
        if ctype == ColumnType.HISTOGRAM:
            # widest bucket scheme wins; narrower chunks pad their top bucket
            # out (cumulative counts -> edge padding preserves totals)
            buckets = max((p[0] for p in val_parts if p[0] is not None),
                          key=lambda bk: bk.num_buckets, default=None)
            rows = [p[1] for p in val_parts]
            b = buckets.num_buckets if buckets is not None else 0
            rows = [np.pad(r, ((0, 0), (0, b - r.shape[1])), mode="edge")
                    if 0 < r.shape[1] < b else r for r in rows]
            vals = np.concatenate(rows) if rows else np.empty((0, b), dtype=np.int64)
            mask = (ts >= start) & (ts <= end)
            return ts[mask], (buckets, vals[mask])
        if ctype == ColumnType.STRING:
            mask = (ts >= start) & (ts <= end)
            flat = [x for p in val_parts for x in p]
            return ts[mask], [x for x, m in zip(flat, mask) if m]
        vals = (val_parts[0] if len(val_parts) == 1
                else np.concatenate(val_parts)).astype(np.float64,
                                                       copy=False)
        # whole span inside the query range (the ODP cold path / full
        # dashboard scan): skip the mask pass — the returned arrays may
        # then VIEW the decoded-chunk cache, so callers must treat
        # read_range output as read-only (they all copy into batches,
        # grids, or encoders)
        if int(ts[0]) >= start and int(ts[-1]) <= end:
            return ts, vals
        mask = (ts >= start) & (ts <= end)
        return ts[mask], vals[mask]

    def chunk_infos(self):
        return [cs.info for cs in self.chunks]

    @property
    def mem_bytes(self) -> int:
        return (sum(cs.nbytes for cs in self.chunks)
                + sum(len(pb.ts) * 16 for pb in self._pending)
                + self._buf_n * 16)


class TracingTimeSeriesPartition(TimeSeriesPartition):
    """Debug variant logging every ingested sample and every chunk
    freeze for one traced series (reference: TimeSeriesPartition.scala:451
    TracingTimeSeriesPartition, enabled per-partkey by the shard's
    StoreConfig.trace_filters).  Overrides the hot methods — the normal
    partition pays nothing for the feature."""

    __slots__ = ()

    def ingest(self, timestamp, values):
        ok = super().ingest(timestamp, values)
        logging.getLogger("filodb.trace").info(
            "TRACE ingest part=%d tags=%s ts=%d values=%s accepted=%s",
            self.part_id, self.tags, timestamp, list(values), ok)
        return ok

    def ingest_block(self, ts, cols):
        """The fast columnar path (C++ container decode) must trace too
        — it is the path production ingestion actually takes."""
        added, dropped = super().ingest_block(ts, cols)
        log = logging.getLogger("filodb.trace")
        for i in range(len(ts)):
            # histogram columns arrive as (buckets, matrix) pairs
            row = [c[1][i].tolist() if isinstance(c, tuple) else c[i]
                   for c in cols]
            log.info("TRACE ingest part=%d tags=%s ts=%d values=%s",
                     self.part_id, self.tags, int(ts[i]), row)
        if dropped:
            log.info("TRACE ingest part=%d dropped=%d out-of-order rows",
                     self.part_id, dropped)
        return added, dropped

    def _log_freeze(self, chunksets):
        log = logging.getLogger("filodb.trace")
        for cs in chunksets:
            log.info("TRACE freeze part=%d chunk_id=%d rows=%d [%d, %d] %dB",
                     self.part_id, cs.info.chunk_id, cs.info.num_rows,
                     cs.info.start_time, cs.info.end_time, cs.nbytes)

    def drain_pending(self):
        out = super().drain_pending()
        self._log_freeze(out)
        return out
