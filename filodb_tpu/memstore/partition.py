"""Per-series partition state: write buffers + frozen chunks.

Equivalent of the reference's TimeSeriesPartition (reference:
core/src/main/scala/filodb.core/memstore/TimeSeriesPartition.scala:64):
appends land in pre-allocated write buffers; when full (or at flush
boundaries) ``switch_buffers`` freezes them into a compressed ``ChunkSet``
(the encodeOneChunkset step, :203-249); out-of-order samples are dropped
(:131-134).  Queries read through ``read_range`` which serves decoded dense
arrays — the device-facing form.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from filodb_tpu.codecs import histcodec
from filodb_tpu.core.chunk import ChunkSet, decode_chunkset, encode_chunkset
from filodb_tpu.core.histogram import HistogramBuckets
from filodb_tpu.core.schemas import ColumnType, Schema


class TimeSeriesPartition:
    __slots__ = ("part_id", "schema", "partkey", "tags", "group",
                 "chunks", "_decoded", "_buf_ts", "_buf_cols", "_buf_n",
                 "_capacity", "_hist_buckets", "_seq", "_unflushed",
                 "out_of_order_dropped", "on_freeze")

    def __init__(self, part_id: int, schema: Schema, partkey: bytes,
                 tags: dict[str, str], group: int, capacity: int = 400):
        self.part_id = part_id
        self.schema = schema
        self.partkey = partkey
        self.tags = tags
        self.group = group
        self.chunks: list[ChunkSet] = []
        self._decoded: dict[int, tuple] = {}   # chunk_id -> (ts, cols)
        self._capacity = capacity
        self._buf_ts = np.empty(capacity, dtype=np.int64)
        self._buf_cols: list = [self._new_col_buffer(c.ctype)
                                for c in schema.data.columns[1:]]
        self._buf_n = 0
        self._hist_buckets: Optional[HistogramBuckets] = None
        self._seq = 0
        self._unflushed: list[ChunkSet] = []
        self.out_of_order_dropped = 0
        # shard hook observing chunk freezes (device grid invalidation)
        self.on_freeze = None

    def _new_col_buffer(self, ctype: ColumnType):
        if ctype == ColumnType.DOUBLE:
            return np.empty(self._capacity, dtype=np.float64)
        if ctype in (ColumnType.LONG, ColumnType.TIMESTAMP, ColumnType.INT):
            return np.empty(self._capacity, dtype=np.int64)
        return []  # STRING / HISTOGRAM: python list, frozen at encode time

    # -- ingest -------------------------------------------------------------

    def ingest(self, timestamp: int, values: Sequence) -> bool:
        """Append one sample.  Returns False for out-of-order drops."""
        if timestamp <= self.latest_timestamp:
            self.out_of_order_dropped += 1
            return False
        # decode histogram blobs first: a bucket-scheme switch mid-stream
        # freezes the current buffer (reference: AddResponse.
        # BucketSchemaMismatch forces a new vector, BinaryVector.scala:231-236)
        decoded = []
        for col, v in zip(self.schema.data.columns[1:], values):
            if col.ctype == ColumnType.HISTOGRAM:
                buckets, counts = histcodec.decode_hist_value(v) \
                    if isinstance(v, (bytes, bytearray)) else v
                if self._hist_buckets is not None and self._buf_n > 0 \
                        and buckets != self._hist_buckets:
                    self.switch_buffers()
                self._hist_buckets = buckets
                decoded.append(np.asarray(counts, dtype=np.int64))
            else:
                decoded.append(v)
        if self._buf_n == self._capacity:
            self.switch_buffers()
        i = self._buf_n
        self._buf_ts[i] = timestamp
        for buf, col, v in zip(self._buf_cols, self.schema.data.columns[1:], decoded):
            if col.ctype in (ColumnType.HISTOGRAM, ColumnType.STRING):
                buf.append(v)
            else:
                buf[i] = v
        self._buf_n = i + 1
        return True

    @property
    def latest_timestamp(self) -> int:
        if self._buf_n:
            return int(self._buf_ts[self._buf_n - 1])
        if self.chunks:
            return self.chunks[-1].info.end_time
        return -1

    @property
    def earliest_timestamp(self) -> int:
        if self.chunks:
            return self.chunks[0].info.start_time
        if self._buf_n:
            return int(self._buf_ts[0])
        return -1

    @property
    def num_chunks(self) -> int:
        return len(self.chunks) + (1 if self._buf_n else 0)

    def switch_buffers(self) -> Optional[ChunkSet]:
        """Freeze the current write buffer into a compressed ChunkSet
        (reference: switchBuffers + encodeOneChunkset)."""
        n = self._buf_n
        if n == 0:
            return None
        cols = []
        for buf, col in zip(self._buf_cols, self.schema.data.columns[1:]):
            if col.ctype == ColumnType.HISTOGRAM:
                cols.append((self._hist_buckets, np.stack(buf[:n])))
            elif col.ctype == ColumnType.STRING:
                cols.append(list(buf[:n]))
            else:
                cols.append(buf[:n].copy())
        cs = encode_chunkset(self.schema, self.partkey, self._buf_ts[:n].copy(),
                             cols, ingestion_seq=self._seq)
        self._seq += 1
        self.chunks.append(cs)
        self._unflushed.append(cs)
        self._buf_n = 0
        self._buf_cols = [self._new_col_buffer(c.ctype)
                          for c in self.schema.data.columns[1:]]
        if self.on_freeze is not None:
            self.on_freeze(cs)
        return cs

    def make_flush_chunks(self) -> list[ChunkSet]:
        """Freeze + drain chunks not yet persisted (reference:
        makeFlushChunks, TimeSeriesPartition.scala:264)."""
        self.switch_buffers()
        out, self._unflushed = self._unflushed, []
        return out

    # -- read ---------------------------------------------------------------

    def _decoded_chunk(self, cs: ChunkSet) -> tuple:
        got = self._decoded.get(cs.info.chunk_id)
        if got is None:
            got = decode_chunkset(self.schema, cs)
            self._decoded[cs.info.chunk_id] = got
        return got

    def drop_decoded_cache(self) -> None:
        self._decoded.clear()

    def read_range(self, start: int, end: int, column_id: Optional[int] = None):
        """All samples with start <= ts <= end as dense arrays.

        Returns (ts[int64], values) where values is float64 for scalar
        columns or (HistogramBuckets, int64[rows, buckets]) for histograms.
        Replaces per-row VectorDataReader iteration with whole-chunk decode +
        concatenation; the windowing kernels do the range math on device.
        """
        cid = self.schema.data.value_column_id if column_id is None else column_id
        col_idx = cid - 1  # data columns after the timestamp
        ctype = self.schema.data.columns[cid].ctype
        ts_parts, val_parts = [], []
        for cs in self.chunks:
            if cs.info.end_time < start or cs.info.start_time > end:
                continue
            ts, cols = self._decoded_chunk(cs)
            ts_parts.append(ts)
            val_parts.append(cols[col_idx])
        if self._buf_n:
            t0 = int(self._buf_ts[0])
            if not (self._buf_ts[self._buf_n - 1] < start or t0 > end):
                ts_parts.append(self._buf_ts[:self._buf_n].copy())
                buf = self._buf_cols[col_idx]
                if ctype == ColumnType.HISTOGRAM:
                    val_parts.append((self._hist_buckets, np.stack(buf[:self._buf_n])))
                elif ctype == ColumnType.STRING:
                    val_parts.append(list(buf[:self._buf_n]))
                else:
                    val_parts.append(buf[:self._buf_n].copy())
        if not ts_parts:
            empty_ts = np.empty(0, dtype=np.int64)
            if ctype == ColumnType.HISTOGRAM:
                return empty_ts, (self._hist_buckets, np.empty((0, 0), dtype=np.int64))
            return empty_ts, np.empty(0, dtype=np.float64)
        ts = np.concatenate(ts_parts)
        if ctype == ColumnType.HISTOGRAM:
            # widest bucket scheme wins; narrower chunks pad their top bucket
            # out (cumulative counts -> edge padding preserves totals)
            buckets = max((p[0] for p in val_parts if p[0] is not None),
                          key=lambda bk: bk.num_buckets, default=None)
            rows = [p[1] for p in val_parts]
            b = buckets.num_buckets if buckets is not None else 0
            rows = [np.pad(r, ((0, 0), (0, b - r.shape[1])), mode="edge")
                    if 0 < r.shape[1] < b else r for r in rows]
            vals = np.concatenate(rows) if rows else np.empty((0, b), dtype=np.int64)
            mask = (ts >= start) & (ts <= end)
            return ts[mask], (buckets, vals[mask])
        if ctype == ColumnType.STRING:
            mask = (ts >= start) & (ts <= end)
            flat = [x for p in val_parts for x in p]
            return ts[mask], [x for x, m in zip(flat, mask) if m]
        vals = np.concatenate(val_parts).astype(np.float64)
        mask = (ts >= start) & (ts <= end)
        return ts[mask], vals[mask]

    def chunk_infos(self):
        return [cs.info for cs in self.chunks]

    @property
    def mem_bytes(self) -> int:
        return sum(cs.nbytes for cs in self.chunks) + self._buf_n * 16
