"""TimeSeriesShard: per-shard ingestion state machine + scan surface.

The heart of ingestion, matching the reference's TimeSeriesShard
(reference: core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:222):

- partition registry: partkey -> part_id -> TimeSeriesPartition (:243,316)
- tag index lookups (:255, PartKeyLuceneIndex)
- flush **groups**: hash(partKey) % groups_per_shard, per-group recovery
  watermarks that skip already-persisted records (:155-157, :390, :488-522)
- flush pipeline: freeze buffers -> write chunks -> write dirty partkeys ->
  index end-time updates -> checkpoint (doFlushSteps :884-974)
- eviction by oldest end-time + bloom filter of evicted keys (:1308-1401)
- ``lookup_partitions`` -> PartLookupResult (:1441-1488)

Single-writer discipline: ``ingest`` must be called from one thread per
shard (the reference's ingestSched); reads take snapshots.  The TPU twist is
the scan surface: ``scan_batch`` materializes matching partitions into one
padded device-ready ChunkBatch instead of per-row iterators.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Iterable, Optional, Sequence

import numpy as np

from filodb_tpu.core.chunk import ChunkBatch, build_batch
from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.record import (IngestRecord, decode_container,
                                    parse_partkey)
from filodb_tpu.core.schemas import ColumnType, Schemas
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.index import PartKeyIndex
from filodb_tpu.memstore.partition import TimeSeriesPartition
from filodb_tpu.store.columnstore import ColumnStore, NullColumnStore, PartKeyRecord
from filodb_tpu.store.metastore import InMemoryMetaStore, MetaStore
from filodb_tpu.utils.bloom import BloomFilter
from filodb_tpu.workload.quota import SeriesQuotaExceeded


class SplitFiltered(Exception):
    """A record's series belongs to the other half of a shard split
    (ISSUE 13): the ingest path drops it here, counted — never an
    error.  Raised only from the NEW-series path, so established series
    of the retained half pay zero overhead."""

    def __init__(self, n_rows: int = 1):
        self.n_rows = n_rows


_FLUSH_METRICS = None


def _flush_m() -> dict:
    """The filodb_flush_* metric objects, resolved once per process."""
    global _FLUSH_METRICS
    if _FLUSH_METRICS is None:
        from filodb_tpu.utils.observability import flush_metrics
        _FLUSH_METRICS = flush_metrics()
    return _FLUSH_METRICS


@dataclasses.dataclass
class PartLookupResult:
    """Outcome of an index lookup (reference: PartLookupResult,
    TimeSeriesShard.scala:1441-1488): in-memory part ids plus partkeys that
    need on-demand paging from the column store."""

    shard: int
    part_ids: np.ndarray
    missing_partkeys: list[bytes]
    first_schema_hash: Optional[int]


@dataclasses.dataclass
class FlushTask:
    """Snapshot handed from the ingest thread to the flush executor
    (reference: FlushGroup, TimeSeriesShard.scala:110-160)."""

    group: int
    parts: list
    dirty: set
    offset: int
    ingestion_time: int


@dataclasses.dataclass
class ShardStats:
    """Counter bundle (reference: TimeSeriesShardStats, :37-108)."""

    rows_ingested: int = 0
    rows_skipped: int = 0
    out_of_order_dropped: int = 0
    partitions_created: int = 0
    partitions_evicted: int = 0
    partitions_purged: int = 0
    chunks_flushed: int = 0
    flushes_done: int = 0
    # integrity subsystem (filodb_tpu/integrity): decode/checksum
    # corruption detected while serving this shard, and how many of
    # those chunks entered quarantine here
    chunks_corrupt: int = 0
    chunks_quarantined: int = 0
    # workload subsystem (filodb_tpu/workload): new series rejected
    # because their tenant hit its active-series quota, and the rows
    # those rejections dropped
    series_quota_rejected: int = 0
    rows_quota_dropped: int = 0
    # elastic resharding (ISSUE 13): rows skipped because their series
    # hashes to the OTHER half of a split — a child replaying its
    # parent's full partition keeps only its half, and a retired parent
    # refuses to re-materialize series its child now owns
    rows_split_filtered: int = 0


class TimeSeriesShard:
    def __init__(self, dataset: str, schemas: Schemas, shard_num: int,
                 config: Optional[StoreConfig] = None,
                 column_store: Optional[ColumnStore] = None,
                 meta_store: Optional[MetaStore] = None):
        self.dataset = dataset
        self.schemas = schemas
        self.shard_num = shard_num
        self.config = config or StoreConfig()
        self.store = column_store or NullColumnStore()
        self.meta = meta_store or InMemoryMetaStore()
        self.index = PartKeyIndex()
        self._lookup_cache: dict = {}
        # bumped whenever a partition leaves the in-memory map (evict /
        # purge): lets the device grid cache skip re-validating every
        # requested pid per query (20k dict walks otherwise dominate
        # host-side serving time at high cardinality)
        self.removal_epoch = 0
        # serializes removal_epoch bumps: evictions fire from ingest,
        # housekeeping, AND (on ODP shards) query threads concurrently; a
        # lost read-modify-write would leave stale grid preps "current"
        self._epoch_lock = threading.Lock()
        self.partitions: dict[int, TimeSeriesPartition] = {}
        self.part_set: dict[bytes, int] = {}
        # part id -> 16-bit schema hash; covers index-only (evicted /
        # recovered) entries so lookups can stay schema-consistent without
        # materializing the partition
        self.part_schema_hash: dict[int, int] = {}
        self._next_part_id = 0
        self.num_groups = self.config.groups_per_shard
        # per-group recovery watermarks: records at offset <= watermark were
        # already persisted pre-restart and are skipped during recovery
        self.group_watermarks = [-1] * self.num_groups
        self._dirty_partkeys: list[set[int]] = [set() for _ in range(self.num_groups)]
        # guards the dirty-set swap (flush prepare), merge-back (failed
        # flush), and ingest-side adds against each other
        self._dirty_lock = threading.Lock()
        self.latest_offset = -1
        # newest sample timestamp seen: drives time-boundary flush
        # scheduling (reference: createFlushTasks time boundaries :804-846)
        self.latest_ingest_ts = -1
        self.evicted_keys = BloomFilter(self.config.evicted_pk_bloom_filter_capacity)
        self.stats = ShardStats()
        # set when an eviction/reclaim bookkeeping invariant broke: the
        # shard FAILS further scans rather than serve stale buffers
        # (the reference kills the process on its reclaim meta check)
        self.integrity_failed: Optional[str] = None
        # store-level corruption detections route back here by identity
        from filodb_tpu import integrity
        integrity.register_shard(self)
        self.ingest_sched_check = None  # optional thread-name assertion hook
        # device-resident chunk grids (HBM arena; memstore/devicestore.py),
        # one per (schema, value column); created lazily on first grid scan
        self.device_caches: dict = {}
        # mesh placement: when set (a jax Device), this shard's grid
        # blocks live on THAT device so the SPMD mesh serving path
        # (parallel/meshgrid.py) reads them in place — the multi-device
        # analog of BlockManager-resident serving
        self.grid_device = None
        # monotone counter observed by the device caches' tail versioning:
        # bumped whenever new rows or chunks could change query results
        self.ingest_epoch = 0
        # counts chunk FREEZES only (a strict subset of ingest_epoch
        # bumps): the encoded chunk set changes exactly on freeze or
        # removal, so the result cache's span table keys on these
        self.freeze_epoch = 0
        self._span_table: Optional[tuple] = None
        self._mutable_floor: Optional[tuple] = None  # (ingest_epoch, ts)
        # flush-time downsampling (reference: ShardDownsampler invoked from
        # doFlushSteps :915-917); set via enable_downsampling()
        self.downsample_publisher = None
        self.downsample_resolutions: tuple[int, ...] = ()
        self._downsamplers: dict[int, object] = {}
        # live rollup subsystem (filodb_tpu/rollup): called after each
        # successful flush with {schema_hash: [(tags, chunkset)]} + the
        # flush ingestion time — the incremental chunk feed the
        # RollupEngine tiers from.  Must never fail the flush.
        self.rollup_listener = None
        # active-series cardinality quota (workload/quota.py): consulted
        # right before a NEW part id is assigned; an over-quota tenant's
        # new series is rejected (rows dropped + counted) while existing
        # series keep ingesting (reference: CardinalityManager/QuotaSource)
        self.series_quota = None
        # data-plane cardinality explorer (ISSUE 6, memstore/cardinality):
        # O(1) churn notes at part-id assignment and evict/purge, plus
        # set_fn-sampled active-series gauges off this shard's index
        from filodb_tpu.memstore.cardinality import CardinalityTracker
        self.cardinality = CardinalityTracker(dataset, shard_num)
        self.cardinality.attach_index(self.index)
        # the FlushScheduler currently driving this shard (node.py /
        # ingest_stream attach it) so the watermark ledger can surface
        # flush-queue depth/age in /admin/shards
        self.flush_scheduler = None
        # elastic resharding (ISSUE 13, coordinator/split.py):
        # - split_ingest_filter: tags -> keep?  Installed on split
        #   CHILDREN (each keeps its half of the parent's hash space
        #   while replaying the parent's partition) and on retired
        #   parents (refuse to re-materialize migrated series).  Checked
        #   only on the new-series path — established retained series
        #   never pay it.
        # - _reshard_memo: pid -> post-split shard, the scan-exclusion
        #   memo filter_resharded() uses between cutover and retire.
        self.split_ingest_filter = None
        self._reshard_memo: dict[int, int] = {}
        self._reshard_memo_key: Optional[tuple] = None
        # serializes split clone/backfill against the flush executor so
        # the (persisted chunks, checkpoints) pair a child inherits is a
        # consistent at-rest snapshot (chunks persist BEFORE checkpoints
        # advance; cloning between the two would double or drop rows)
        self.split_clone_lock = threading.Lock()

    def enable_downsampling(self, publisher, resolutions_ms) -> None:
        self.downsample_publisher = publisher
        self.downsample_resolutions = tuple(resolutions_ms)
        self._downsamplers = {}

    def close(self) -> None:
        """Release registry-held callbacks (Gauge.remove contract):
        everything this shard registered against process-wide state must
        be unwound or the registry keeps the shard alive and keeps
        exporting rows for it.  Subclasses extend (ODP deregisters its
        page-cache pool)."""
        self.cardinality.close()

    # ------------------------------------------------------------------ ingest

    def ingest_container(self, container: bytes, offset: int) -> int:
        fast = self._ingest_container_fast(container, offset)
        if fast is not None:
            return fast
        return self.ingest(decode_container(container, self.schemas), offset)

    def _ingest_container_fast(self, container: bytes, offset: int
                               ) -> Optional[int]:
        """Columnar ingest: C++ container decode + per-series batch append
        (native/ingestfast.py).  Histogram columns arrive blob-expanded
        (HistColumn) and batch-append when a series' rows share one
        bucket scheme and width — the rare mixed-scheme run falls back
        to per-record ingest for just that series.  Returns None when
        this container can't take the fast path (string columns, mixed
        schemas, no compiler) — the caller then runs the per-record
        path.  Semantics match :meth:`ingest` exactly;
        tests/test_memstore.py proves equivalence on out-of-order and
        watermark-skip data."""
        from filodb_tpu.native import ingestfast

        dec = ingestfast.decode(container, self.schemas)
        if dec is None:
            return None
        if self.ingest_sched_check is not None:
            self.ingest_sched_check()
        if dec.num_records == 0:
            self.latest_offset = max(self.latest_offset, offset)
            return 0
        schema = self.schemas.by_hash(dec.schema_hash)
        ts, cols, uniq_idx = dec.ts, dec.cols, dec.uniq_idx
        groups_r = (dec.part_hashes % np.uint32(self.num_groups)).astype(
            np.int64)
        # recovery watermark skip (reference IngestConsumer :488-522);
        # steady state short-circuits on max(watermarks) < offset
        if offset <= max(self.group_watermarks):
            keep = offset > np.asarray(self.group_watermarks)[groups_r]
            skipped = int((~keep).sum())
            if skipped:
                self.stats.rows_skipped += skipped
                ts, uniq_idx = ts[keep], uniq_idx[keep]
                cols = [c[keep] for c in cols]
        n_uniq = len(dec.partkeys)
        order = np.argsort(uniq_idx, kind="stable")
        ts_s = ts[order]
        cols_s = [c[order] for c in cols]
        counts = np.bincount(uniq_idx, minlength=n_uniq)
        starts = np.concatenate(([0], np.cumsum(counts)))
        added_total = 0
        maxint = np.iinfo(np.int64).max
        for u in range(n_uniq):
            s0, s1 = int(starts[u]), int(starts[u + 1])
            if s0 == s1:
                continue  # every record of this series was watermark-skipped
            first = int(dec.uniq_first[u])
            try:
                part = self._get_or_add_partition_pk(
                    dec.partkeys[u], schema, int(dec.part_hashes[first]),
                    int(ts_s[s0]))
            except SeriesQuotaExceeded:
                # over-quota NEW series: its rows drop, the rest of the
                # container keeps ingesting (existing series unaffected)
                self.stats.rows_quota_dropped += s1 - s0
                self.series_quota.note_dropped_samples(
                    parse_partkey(dec.partkeys[u]), s1 - s0)
                continue
            except SplitFiltered:
                # the series belongs to the other half of a split: a
                # child keeps only its half of the replayed parent
                # partition (ISSUE 13)
                self.stats.rows_split_filtered += s1 - s0
                continue
            added, dropped = self._ingest_series_block(
                part, ts_s[s0:s1], [c[s0:s1] for c in cols_s])
            added_total += added
            self.stats.rows_ingested += added
            self.stats.out_of_order_dropped += dropped
            if self.index.end_time(part.part_id) != maxint:
                self.index.mark_active(part.part_id)
            with self._dirty_lock:
                self._dirty_partkeys[int(groups_r[first])].add(part.part_id)
        if len(ts):
            self.latest_ingest_ts = max(self.latest_ingest_ts,
                                        int(ts.max()))
        self.latest_offset = max(self.latest_offset, offset)
        if added_total:
            self.ingest_epoch += 1
        return added_total

    @staticmethod
    def _ingest_series_block(part, ts: np.ndarray, cols: list
                             ) -> tuple[int, int]:
        """Batch-append one series' rows.  HistColumn entries become
        (bucket scheme, counts matrix) pairs when the run is uniform
        (one scheme, one width — the overwhelmingly common case);
        otherwise the run ingests per record so bucket-scheme-switch
        semantics (buffer freeze) match the slow path exactly."""
        from filodb_tpu.native.ingestfast import HistColumn
        block_cols: list = []
        uniform = True
        for c in cols:
            if not isinstance(c, HistColumn):
                block_cols.append(c)
                continue
            if len(c.schemes) > 1 and \
                    (c.scheme_idx != c.scheme_idx[0]).any():
                uniform = False
                break
            nb0 = int(c.nbuckets[0])
            if (c.nbuckets != nb0).any():
                uniform = False
                break
            block_cols.append((c.schemes[int(c.scheme_idx[0])],
                               c.counts[:, :nb0]))
        if uniform:
            return part.ingest_block(ts, block_cols)
        added = dropped = 0
        for i in range(len(ts)):
            # .copy(): a buffered row view would pin the whole container
            # counts matrix until the buffer freezes
            row = [(c.schemes[int(c.scheme_idx[i])],
                    c.counts[i, :int(c.nbuckets[i])].copy())
                   if isinstance(c, HistColumn) else c[i] for c in cols]
            if part.ingest(int(ts[i]), row):
                added += 1
            else:
                dropped += 1
        return added, dropped

    def ingest(self, records: Iterable[IngestRecord], offset: int) -> int:
        """Ingest a batch of records at a stream offset.  Returns rows added.

        Group watermark skipping mirrors the reference's IngestConsumer
        (:488-522): during recovery, a record whose flush group checkpointed
        beyond ``offset`` is already persisted — skip it.
        """
        if self.ingest_sched_check is not None:
            self.ingest_sched_check()
        n = 0
        for rec in records:
            group = rec.part_hash % self.num_groups
            if offset <= self.group_watermarks[group]:
                self.stats.rows_skipped += 1
                continue
            try:
                part = self._get_or_add_partition(rec)
            except SeriesQuotaExceeded:
                self.stats.rows_quota_dropped += 1
                self.series_quota.note_dropped_samples(rec.tags)
                continue
            except SplitFiltered:
                self.stats.rows_split_filtered += 1
                continue
            if part.ingest(rec.timestamp, rec.values):
                n += 1
                self.stats.rows_ingested += 1
            else:
                self.stats.out_of_order_dropped += 1
            if self.index.end_time(part.part_id) != np.iinfo(np.int64).max:
                self.index.mark_active(part.part_id)
            with self._dirty_lock:
                self._dirty_partkeys[group].add(part.part_id)
            if rec.timestamp > self.latest_ingest_ts:
                self.latest_ingest_ts = rec.timestamp
        self.latest_offset = max(self.latest_offset, offset)
        if n:
            self.ingest_epoch += 1
        return n

    def _get_or_add_partition(self, rec: IngestRecord) -> TimeSeriesPartition:
        return self._get_or_add_partition_pk(
            rec.partkey(), self.schemas.by_hash(rec.schema_hash),
            rec.part_hash, rec.timestamp, tags=rec.tags)

    def _get_or_add_partition_pk(self, pk: bytes, schema, part_hash: int,
                                 timestamp: int, tags: Optional[dict] = None
                                 ) -> TimeSeriesPartition:
        """Partition registry lookup/creation keyed by raw partkey bytes;
        tags are parsed lazily so the columnar fast path never builds a
        tag dict for known series (reference: partSet O(1) lookup by
        ingest record, TimeSeriesShard.scala:1091)."""
        pid = self.part_set.get(pk)
        if pid is not None:
            part = self.partitions.get(pid)
            if part is not None:
                return part
            # index-only entry (recovered or paged-out): re-materialize the
            # partition under its existing part id, keeping index lifecycle
            rtags = tags if tags is not None else parse_partkey(pk)
            part = self._partition_cls(rtags)(
                pid, schema, pk, rtags, part_hash % self.num_groups,
                capacity=self.config.max_chunks_size)
            part.on_freeze = self._on_chunk_freeze
            part.on_corrupt = self.note_corrupt_chunk
            self.partitions[pid] = part
            self.index.mark_active(pid)
            return part
        # evicted-key bloom check: a maybe-evicted key re-reads its true
        # start time from the column store lifecycle (reference :1103-1122)
        if tags is None:
            tags = parse_partkey(pk)
        if self.split_ingest_filter is not None \
                and not self.split_ingest_filter(tags):
            raise SplitFiltered()
        if self.series_quota is not None \
                and not self.series_quota.allow_new_series(
                    tags, shard=self.shard_num):
            self.stats.series_quota_rejected += 1
            tenant = self.series_quota.tenant_of(tags)
            raise SeriesQuotaExceeded(
                tenant, self.series_quota.active(tenant),
                self.series_quota.limit_for(tenant) or 0)
        start_time = timestamp
        pid = self._next_part_id
        self._next_part_id += 1
        group = part_hash % self.num_groups
        part = self._partition_cls(tags)(
            pid, schema, pk, tags, group,
            capacity=self.config.max_chunks_size)
        part.on_freeze = self._on_chunk_freeze
        part.on_corrupt = self.note_corrupt_chunk
        self.partitions[pid] = part
        self.part_set[pk] = pid
        self.part_schema_hash[pid] = schema.schema_hash
        self.index.add_partkey(pid, pk, tags, start_time)
        self.stats.partitions_created += 1
        self.cardinality.note_created()
        return part

    def _partition_cls(self, tags: dict[str, str]):
        """TracingTimeSeriesPartition for series matching the
        `trace-filters` tag subset (reference: TimeSeriesPartition.scala:451
        TracingTimeSeriesPartition); the normal class otherwise."""
        tf = self.config.trace_filters
        if tf and all(tags.get(k) == str(v) for k, v in tf.items()):
            from filodb_tpu.memstore.partition import \
                TracingTimeSeriesPartition
            return TracingTimeSeriesPartition
        return TimeSeriesPartition

    def create_partition(self, schema_name: str, tags: dict[str, str],
                         start_time: int) -> TimeSeriesPartition:
        """Direct partition creation for tests/recovery paths."""
        from filodb_tpu.core.record import canonical_partkey, partition_hash
        rec = IngestRecord(self.schemas[schema_name].schema_hash, tags,
                           start_time, (), 0, partition_hash(tags))
        return self._get_or_add_partition(rec)

    # ------------------------------------------------------------------ flush

    def prepare_flush_group(self, group: int,
                            ingestion_time: Optional[int] = None
                            ) -> "FlushTask":
        """Ingest-thread half of a pipelined flush: O(partitions-in-group)
        buffer detaches plus state snapshots; no encoding, no IO
        (reference: prepareFlushGroup, TimeSeriesShard.scala:756-774).
        The returned task runs on a flush executor via
        :meth:`run_flush_task`; tasks for the SAME group must run in
        submission order (the scheduler serializes per group)."""
        itime = ingestion_time if ingestion_time is not None \
            else int(time.time() * 1000)
        parts = [p for p in self.partitions.values() if p.group == group]
        for part in parts:
            part.freeze_raw()
        with self._dirty_lock:
            dirty = self._dirty_partkeys[group]
            self._dirty_partkeys[group] = set()
        return FlushTask(group=group, parts=parts, dirty=dirty,
                         offset=self.latest_offset, ingestion_time=itime)

    def run_flush_task(self, task: "FlushTask") -> int:
        """Flush-executor half: encode pending buffers (frozen at prepare
        time — never the live write buffer), write chunks, downsample,
        persist partkeys, checkpoint (the doFlushSteps pipeline,
        reference :884-974).  Returns chunksets written.  On failure the
        dirty partkeys are re-queued so a later flush persists them.

        Instrumented per ISSUE 2 (reference: Kamon spans around flush,
        TimeSeriesShard.scala:888-891): one span + the filodb_flush_*
        metrics per task; failures count before re-raising."""
        from filodb_tpu.utils.observability import TRACER
        m = _flush_m()
        t0 = time.perf_counter()
        try:
            with TRACER.span("memstore.flush", dataset=self.dataset,
                             shard=self.shard_num, group=task.group):
                n = self._run_flush_task(task)
        except BaseException:
            m["failures"].inc(dataset=self.dataset)
            raise
        finally:
            m["flush_seconds"].observe(time.perf_counter() - t0,
                                       dataset=self.dataset)
        m["chunks"].inc(n, dataset=self.dataset)
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("flush", dataset=self.dataset, shard=self.shard_num,
                      group=task.group, chunks=n,
                      seconds=round(time.perf_counter() - t0, 6))
        return n

    def _run_flush_task(self, task: "FlushTask") -> int:
        # split_clone_lock scopes the persist->checkpoint pair: a split
        # clone (coordinator/split.py) holding it sees either none or
        # all of one flush task, so the child's inherited (chunks,
        # checkpoints) snapshot keeps the parent's own recovery
        # invariant (checkpoint only covers persisted rows).  The sqlite
        # layer serializes writers anyway, so cross-group flush tasks
        # lose no real concurrency here.
        with self.split_clone_lock:
            return self._run_flush_task_locked(task)

    def _run_flush_task_locked(self, task: "FlushTask") -> int:
        collected: list[tuple] = []  # (part, its fresh chunksets)
        try:
            chunksets = []
            ds_pairs: dict[int, list] = {}  # schema_hash -> [(tags, cs)]
            for part in task.parts:
                fresh = part.collect_flush_chunks()
                if fresh:
                    collected.append((part, fresh))
                chunksets.extend(fresh)
                if fresh and (self.downsample_publisher is not None
                              or self.rollup_listener is not None):
                    ds_pairs.setdefault(part.schema.schema_hash, []).extend(
                        (part.tags, cs) for cs in fresh)
            if chunksets:
                self.store.write_chunks(self.dataset, self.shard_num,
                                        chunksets, task.ingestion_time)
            if self.downsample_publisher is not None:
                for shash, pairs in ds_pairs.items():
                    self._downsampler_for(shash).downsample_chunksets(pairs)
            if task.dirty:
                recs = [PartKeyRecord(self.index.partkey(pid),
                                      self.index.start_time(pid),
                                      self.index.end_time(pid),
                                      self.shard_num,
                                      self.partitions[pid].schema.schema_hash)
                        for pid in task.dirty if pid in self.partitions]
                self.store.write_part_keys(self.dataset, self.shard_num, recs)
        except BaseException:
            # nothing persisted for sure: requeue both the chunksets and
            # the dirty partkeys so the next flush retries them (store
            # writes are idempotent by chunk id / partkey upsert)
            for part, fresh in collected:
                part.requeue_unflushed(fresh)
            with self._dirty_lock:
                self._dirty_partkeys[task.group] |= task.dirty
            raise
        # checkpoint only after chunks+partkeys persisted (reference :949-960)
        self.meta.write_checkpoint(self.dataset, self.shard_num, task.group,
                                   task.offset)
        if self.rollup_listener is not None and ds_pairs:
            # hand the fresh chunksets to the live rollup engine AFTER
            # the flush persisted+checkpointed (the engine's restart
            # catch-up reads the store by ingestion time, so a crash
            # between persist and handoff replays, never loses)
            try:
                self.rollup_listener(ds_pairs, task.ingestion_time)
            except Exception:  # noqa: BLE001 — rollup must never fail a flush
                import traceback
                traceback.print_exc()
        self.group_watermarks[task.group] = max(
            self.group_watermarks[task.group], task.offset)
        self.stats.chunks_flushed += len(chunksets)
        self.stats.flushes_done += 1
        # proactive HBM reclaim off the query path: trim device caches
        # to (1-headroom) of budget while we're already on the flush
        # executor (the reference's background headroom task)
        frac = self.config.device_headroom_frac
        if frac > 0:
            for cache in list(self.device_caches.values()):
                cache.ensure_headroom(frac)
        return len(chunksets)

    def flush_group(self, group: int, ingestion_time: Optional[int] = None) -> int:
        """Synchronous flush of one group (prepare + run inline)."""
        return self.run_flush_task(self.prepare_flush_group(group,
                                                            ingestion_time))

    def _downsampler_for(self, schema_hash: int):
        ds = self._downsamplers.get(schema_hash)
        if ds is None:
            from filodb_tpu.downsample.sharddown import ShardDownsampler
            ds = ShardDownsampler(self.dataset, self.shard_num,
                                  self.schemas.by_hash(schema_hash),
                                  self.downsample_publisher,
                                  self.downsample_resolutions)
            self._downsamplers[schema_hash] = ds  # filolint: disable=bounded-cache — keyed by schema hash, bounded by the configured schema set
        return ds

    def flush_all(self, ingestion_time: Optional[int] = None) -> int:
        return sum(self.flush_group(g, ingestion_time)
                   for g in range(self.num_groups))

    # ------------------------------------------------------------- lifecycle

    def bump_removal_epoch(self) -> None:
        """Atomic removal-epoch increment; see ``_epoch_lock``."""
        with self._epoch_lock:
            self.removal_epoch += 1

    def note_corrupt_chunk(self, err, newly_quarantined: bool) -> None:
        """Partition/store hook: a chunk of this shard failed checksum
        or decode (already quarantined + logged by the integrity
        funnel); keep the per-shard tally the tentpole asks for."""
        self.stats.chunks_corrupt += 1
        if newly_quarantined:
            self.stats.chunks_quarantined += 1
            # grid plans staged from this chunk must revalidate, so the
            # DEVICE serving path excludes the quarantined chunk exactly
            # like the host path's read_range does
            self.bump_removal_epoch()

    def _check_integrity(self) -> None:
        """Hard tripwire: once eviction/reclaim bookkeeping is known
        broken, refuse to serve (stale buffers are worse than errors)."""
        if self.integrity_failed is not None:
            from filodb_tpu.integrity import IntegrityInvariantError
            raise IntegrityInvariantError(
                f"shard {self.shard_num} failed integrity: "
                f"{self.integrity_failed}")

    def evict_partitions(self, n: int) -> int:
        """Evict up to n longest-stopped partitions (reference :1308-1401).
        Their data must already be flushed; in-memory state is dropped and
        the partkey recorded in the evicted bloom filter."""
        victims = self.index.part_ids_ordered_by_end_time(n)
        for pid in victims:
            part = self.partitions.pop(pid, None)
            if part is None:
                continue
            self.bump_removal_epoch()
            self.part_set.pop(part.partkey, None)
            self.evicted_keys.add(part.partkey)
            self.index.remove([pid])
            if self.series_quota is not None:
                self.series_quota.note_removed(part.tags)
            self.stats.partitions_evicted += 1
            self.cardinality.note_removed("evict")
        return len(victims)

    def purge_expired(self, retention_ms: int, now_ms: int) -> int:
        """Drop partitions whose data aged out entirely (reference :776-795)."""
        cutoff = now_ms - retention_ms
        doomed = [pid for pid, p in self.partitions.items()
                  if p.latest_timestamp < cutoff]
        for pid in doomed:
            part = self.partitions.pop(pid)
            self.bump_removal_epoch()
            self.part_set.pop(part.partkey, None)
            self.index.remove([pid])
            if self.series_quota is not None:
                self.series_quota.note_removed(part.tags)
            self.stats.partitions_purged += 1
            self.cardinality.note_removed("purge")
        return len(doomed)

    # ------------------------------------------------- elastic resharding

    def _resharded_shard(self, pid: int, total: int, spread: int) -> int:
        """The shard this part id's series routes to under a
        ``total``-shard topology, memoized per pid (tags parse + two
        hashes otherwise repeat on every post-cutover scan)."""
        key = (total, spread)
        if self._reshard_memo_key != key:
            self._reshard_memo = {}
            self._reshard_memo_key = key
        got = self._reshard_memo.get(pid)
        if got is None:
            from filodb_tpu.parallel.shardmap import shard_of_tags
            got = self._reshard_memo[pid] = shard_of_tags(  # filolint: disable=bounded-cache — keyed by part id, bounded by this shard's partition registry; dropped whole on (total, spread) change
                self.index.tags(pid), total, spread)
        return got

    def filter_resharded(self, lookup: PartLookupResult, total: int,
                         spread: int) -> PartLookupResult:
        """Scan-time exclusion for a split PARENT between cutover and
        retire (ISSUE 13): drop series that now belong to a child shard
        under the ``total``-shard topology.  The parent keeps a full
        superset of the data until retire purges it (abort stays
        lossless), so every post-cutover scan must slice off the
        migrated half or the child's answers double-count."""
        from filodb_tpu.parallel.shardmap import shard_of_tags
        keep = [pid for pid in lookup.part_ids
                if self._resharded_shard(int(pid), total, spread)
                == self.shard_num]
        missing = [pk for pk in lookup.missing_partkeys
                   if shard_of_tags(parse_partkey(pk), total, spread)
                   == self.shard_num]
        if len(keep) == len(lookup.part_ids) \
                and len(missing) == len(lookup.missing_partkeys):
            return lookup
        return PartLookupResult(lookup.shard,
                                np.asarray(keep, dtype=np.int32), missing,
                                lookup.first_schema_hash)

    def purge_resharded(self, total: int, spread: int) -> list[bytes]:
        """RETIRE a split parent's migrated half: drop every in-memory
        partition (and index entry) whose series now belongs to a child
        shard.  Returns the purged partkeys so the caller can delete
        the persisted copies too.  Runs on the control plane AFTER the
        grace window — the children have been serving this data since
        cutover."""
        doomed = []
        for pid in list(self.partitions):
            if self._resharded_shard(pid, total, spread) != self.shard_num:
                doomed.append(pid)
        from filodb_tpu.parallel.shardmap import shard_of_tags
        # index-only entries (evicted / recovered, no live partition)
        # migrate too — their partkeys still feed lookups and ODP
        for pk, pid in list(self.part_set.items()):
            if pid not in self.partitions \
                    and shard_of_tags(parse_partkey(pk), total,
                                      spread) != self.shard_num:
                doomed.append(pid)
        purged: list[bytes] = []
        for pid in doomed:
            part = self.partitions.pop(pid, None)
            pk = part.partkey if part is not None else self.index.partkey(pid)
            self.bump_removal_epoch()
            self.part_set.pop(pk, None)
            self.part_schema_hash.pop(pid, None)
            self.index.remove([pid])
            if self.series_quota is not None:
                tags = part.tags if part is not None else parse_partkey(pk)
                self.series_quota.note_removed(tags)
            self.stats.partitions_purged += 1
            self.cardinality.note_removed("purge")
            purged.append(pk)
        if purged:
            self._lookup_cache.clear()
        return purged

    def mark_stopped_series(self, now_ms: int, stale_ms: int) -> int:
        """Set index end-times for series that stopped ingesting (reference:
        updateIndexWithEndTime during flush, :1037-1057)."""
        n = 0
        for pid, part in self.partitions.items():
            if part.latest_timestamp < now_ms - stale_ms \
                    and self.index.end_time(pid) == np.iinfo(np.int64).max:
                self.index.update_end_time(pid, part.latest_timestamp)
                n += 1
        return n

    # ------------------------------------------------------------------ query

    def lookup_partitions(self, filters: Sequence[ColumnFilter],
                          start_time: int, end_time: int,
                          limit: Optional[int] = None) -> PartLookupResult:
        """Index lookup restricted to ONE schema — the first matched, like the
        reference's MultiSchemaPartitionsExec runtime schema discovery
        (exec/MultiSchemaPartitionsExec.scala:41-85).  Ids whose partitions
        are not in memory surface as ``missing_partkeys`` for on-demand
        paging.

        Repeated dashboard lookups are cached keyed on (filters, range,
        index version): at 100k+ series the postings walk dominates served
        query latency otherwise."""
        # len(partitions) covers re-materialization of index-only entries
        # (which may not bump the index version); eviction bumps it.
        key = (tuple(filters), start_time, end_time, limit,
               self.index.version, len(self.partitions))
        cached = self._lookup_cache.get(key)
        if cached is not None:
            return cached
        result = self._lookup_partitions_uncached(filters, start_time,
                                                  end_time, limit)
        if len(self._lookup_cache) > 64:
            self._lookup_cache.clear()
        self._lookup_cache[key] = result
        return result

    def _lookup_partitions_uncached(self, filters, start_time, end_time,
                                    limit) -> PartLookupResult:
        ids = self.index.part_ids_from_filters(filters, start_time, end_time,
                                               limit)
        first_schema = None
        in_mem: list[int] = []
        missing: list[bytes] = []
        for i in ids:
            pid = int(i)
            part = self.partitions.get(pid)
            if part is None:
                missing.append(self.index.partkey(pid))
                continue
            if first_schema is None:
                first_schema = part.schema.schema_hash
            if part.schema.schema_hash == first_schema:
                in_mem.append(pid)
        return PartLookupResult(self.shard_num, np.asarray(in_mem, dtype=np.int32),
                                missing, first_schema)

    def chunk_span_table(self):
        """Flat ``(pid, chunk_id, start_time, end_time)`` int64 arrays
        over every in-memory partition's encoded chunks — the result
        cache's immutability digest source (query/resultcache.py).
        Cached per (freeze_epoch, removal_epoch, index version,
        partition count): the encoded chunk set changes exactly on
        freeze/removal, so live per-row ingest never rebuilds it."""
        key = (self.freeze_epoch, self.removal_epoch, self.index.version,
               len(self.partitions))
        tbl = self._span_table
        if tbl is not None and tbl[0] == key:
            return tbl[1]
        pid_l: list = []
        cid_l: list = []
        cs_l: list = []
        ce_l: list = []
        for pid, part in list(self.partitions.items()):
            with part._lock:
                for cs in part.chunks:
                    pid_l.append(pid)
                    cid_l.append(cs.info.chunk_id)
                    cs_l.append(cs.info.start_time)
                    ce_l.append(cs.info.end_time)
        arrs = (np.asarray(pid_l, np.int64), np.asarray(cid_l, np.int64),
                np.asarray(cs_l, np.int64), np.asarray(ce_l, np.int64))
        self._span_table = (key, arrs)
        return arrs

    def mutable_floor(self) -> Optional[int]:
        """Earliest mutable (write-buffer / pending-encode) row
        timestamp across ALL partitions, or None when everything is
        encoded — the result cache's closed-segment probe, cached per
        ingest epoch so a burst of queries between ingest batches pays
        one partition walk.  Deliberately filter-independent: an
        unmatched partition's buffer marking a segment open only costs
        a cache miss, never staleness."""
        # capture the epoch BEFORE the walk (chunk_span_table does the
        # same): a row ingested mid-walk bumps the epoch and must force
        # a recompute — caching the walk under the post-bump epoch
        # would hide that row until the NEXT ingest
        epoch = self.ingest_epoch
        mf = self._mutable_floor
        if mf is not None and mf[0] == epoch:
            return mf[1]
        lo: Optional[int] = None
        for part in list(self.partitions.values()):
            mt = part.mutable_floor()
            if mt is not None and (lo is None or mt < lo):
                lo = mt
        self._mutable_floor = (epoch, lo)
        return lo

    def _partition_for_scan(self, part_id: int) -> Optional[TimeSeriesPartition]:
        """Resolve a part id for scanning.  The ODP shard overrides this to
        consult its paged-partition cache as well."""
        return self.partitions.get(part_id)

    def grid_partition(self, part_id: int) -> Optional[TimeSeriesPartition]:
        """Resolve a part id for the DEVICE GRID (devicestore.py block
        builds and plan validation).  The ODP shard overrides this to
        serve PAGED partitions too — paged-in history registers as grid
        blocks, so a repeat dashboard hit over evicted ranges serves at
        device speed (reference: DemandPagedChunkStore.scala:34 pages
        straight into block memory and serves identically)."""
        return self.partitions.get(part_id)

    # --------------------------------------------------- device-resident scan

    def _on_chunk_freeze(self, cs) -> None:
        self.ingest_epoch += 1
        self.freeze_epoch += 1
        for (shash, _cid), cache in self.device_caches.items():
            if shash == cs.schema_hash or cs.schema_hash == 0:
                cache.note_freeze(cs)

    def device_cache(self, schema_hash: int, column_id: int,
                     hist: bool = False):
        cache = self.device_caches.get((schema_hash, column_id))
        if cache is None:
            from filodb_tpu.memstore.devicestore import DeviceGridCache
            cache = DeviceGridCache(self, schema_hash, column_id,
                                    self.config.device_cache_bytes,
                                    self.config.grid_step_ms, hist=hist)
            self.device_caches[(schema_hash, column_id)] = cache  # filolint: disable=bounded-cache — keyed by (schema, column); each cache holds its own byte budget
        return cache

    def _grid_cache_for(self, part_ids: Sequence[int],
                        column_id: Optional[int]):
        """Shared grid-eligibility preamble: resolve the value column off
        the first partition, require a DOUBLE or HISTOGRAM column, fetch
        the cache.  The ORIGINAL ``part_ids`` object is handed to the
        cache (not a fresh int list): the cache memoizes its per-lookup
        prep on that object's identity, which is only sound because the
        shard's lookup cache keeps the array alive and stable."""
        if len(part_ids) == 0:
            return None
        first = self.grid_partition(int(part_ids[0]))
        if first is None:
            return None
        cid = first.schema.data.value_column_id if column_id is None \
            else column_id
        ctype = first.schema.data.columns[cid].ctype
        if ctype not in (ColumnType.DOUBLE, ColumnType.HISTOGRAM):
            return None
        return self.device_cache(first.schema.schema_hash, cid,
                                 hist=(ctype == ColumnType.HISTOGRAM)), \
            part_ids

    def scan_grid(self, part_ids: Sequence[int], func, steps0: int,
                  nsteps: int, step_ms: int, window_ms: int,
                  column_id: Optional[int] = None, fargs: tuple = ()):
        """Serve a windowed range function directly from the device-resident
        grid (memstore/devicestore.py).  Returns ``(tags_list, vals,
        bucket_tops)`` — vals ``[S, T]`` for scalar columns, ``[S, T, hb]``
        per-bucket (with bucket_tops set) for histogram columns — or None
        when the fast path cannot serve this query; the caller then uses
        :meth:`scan_batch` + the general kernels.  This is the serving
        seam the reference places at block memory (queries read encoded
        chunks straight from BlockManager memory, never re-copying them)."""
        got = self._grid_cache_for(part_ids, column_id)
        if got is None:
            return None
        cache, ids = got
        served = cache.scan_rate(ids, func, steps0, nsteps, step_ms,
                                 window_ms, fargs)
        if served is None:
            return None
        vals, tops = served
        tags_list = []
        for pid in ids:
            part = self.grid_partition(int(pid))
            if part is None:
                return None   # concurrently evicted mid-query: fall back
            tags_list.append(part.tags)
        return tags_list, vals, tops

    def scan_grid_grouped(self, part_ids: Sequence[int], func, steps0: int,
                          nsteps: int, step_ms: int, window_ms: int,
                          group_ids: Sequence[int], num_groups: int,
                          op: str, column_id: Optional[int] = None,
                          fargs: tuple = ()):
        """Fused ``agg by (g)(rate(...))`` from the device grid: the
        aggregation happens on device, so only [G, T] partials come back
        (see DeviceGridCache.scan_rate_grouped).  Returns the mergeable
        state dict or None to fall back."""
        got = self._grid_cache_for(part_ids, column_id)
        if got is None:
            return None
        cache, ids = got
        return cache.scan_rate_grouped(ids, func, steps0, nsteps, step_ms,
                                       window_ms, group_ids, num_groups, op,
                                       fargs)

    def mesh_grid_plan(self, part_ids: Sequence[int], func, steps0: int,
                       nsteps: int, step_ms: int, window_ms: int,
                       group_ids: Sequence[int], fargs: tuple = ()):
        """Device-resident staging for the SPMD mesh serving path
        (devicestore.mesh_plan); None -> host-batch mesh fallback."""
        got = self._grid_cache_for(part_ids, None)
        if got is None:
            return None
        cache, ids = got
        return cache.mesh_plan(ids, func, steps0, nsteps, step_ms,
                               window_ms, group_ids, fargs)

    def pin_grid_device(self, device) -> None:
        """Pin this shard's grid blocks to a mesh device so the SPMD
        serving path (parallel/meshgrid.py) reads them in place — the
        multi-device analog of BlockManager-resident serving.  Re-pins
        invalidate resident blocks (they live on the old device); the
        common single-device -> mesh transition, where blocks already
        sit on the backend default device, keeps them."""
        if device is self.grid_device:
            return
        prev = self.grid_device
        self.grid_device = device
        if prev is None:
            import jax
            if device is jax.devices()[0]:
                return          # unpinned blocks already live there
        for cache in list(self.device_caches.values()):
            cache.note_repin()

    def scan_batch(self, part_ids: Sequence[int], start_time: int, end_time: int,
                   column_id: Optional[int] = None
                   ) -> tuple[list[dict], Optional[ChunkBatch]]:
        """Materialize partitions into one padded ChunkBatch + tag dicts.
        This is the TPU replacement for scanPartitions/RawDataRangeVector
        iteration (reference :1490, SelectRawPartitionsExec)."""
        self._check_integrity()
        tags_list, ts_list, val_list = [], [], []
        hist = None  # locked by the first partition: one value type per batch
        bucket_tops = None
        for pid in part_ids:
            part = self._partition_for_scan(int(pid))
            if part is None:
                continue
            cid = part.schema.data.value_column_id if column_id is None else column_id
            ctype = part.schema.data.columns[cid].ctype
            is_hist = ctype == ColumnType.HISTOGRAM
            if hist is None:
                hist = is_hist
            elif is_hist != hist:
                continue  # mixed schemas: callers scan one schema at a time
            ts, vals = part.read_range(start_time, end_time, cid)
            tags_list.append(part.tags)
            if is_hist:
                buckets, rows = vals
                if buckets is not None:
                    tops = buckets.bucket_tops()
                    if bucket_tops is None or len(tops) > len(bucket_tops):
                        bucket_tops = tops
                ts_list.append(ts)
                val_list.append(rows.astype(np.float64))
            else:
                ts_list.append(ts)
                val_list.append(vals)
        if not tags_list:
            return [], None
        if hist:
            if bucket_tops is None:
                bucket_tops = np.empty(0, dtype=np.float64)
            b = len(bucket_tops)
            val_list = [v if v.shape[1] == b
                        else np.zeros((0, b)) if v.size == 0
                        else np.pad(v, ((0, 0), (0, b - v.shape[1])), mode="edge")
                        if v.shape[1] < b else v[:, :b] for v in val_list]
            batch = build_batch(ts_list, val_list, pad_to=self.config.batch_row_pad,
                                hist=True, bucket_tops=bucket_tops,
                                pad_series_to=_round_up(len(tags_list),
                                                        self.config.batch_series_pad))
        else:
            batch = build_batch(ts_list, val_list, pad_to=self.config.batch_row_pad,
                                pad_series_to=_round_up(len(tags_list),
                                                        self.config.batch_series_pad))
        return tags_list, batch

    # ------------------------------------------------------------- metadata

    def label_values(self, label: str, filters: Sequence[ColumnFilter] = (),
                     start: int = 0, end: int = np.iinfo(np.int64).max,
                     limit: Optional[int] = None) -> list[str]:
        return self.index.label_values(label, filters, start, end, limit)

    def label_names(self, filters: Sequence[ColumnFilter] = (),
                    start: int = 0, end: int = np.iinfo(np.int64).max) -> list[str]:
        return self.index.label_names(filters, start, end)

    def part_keys(self, filters: Sequence[ColumnFilter], start: int, end: int,
                  limit: Optional[int] = None) -> list[dict[str, str]]:
        ids = self.index.part_ids_from_filters(filters, start, end, limit)
        return [self.index.tags(int(i)) for i in ids]

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    @property
    def mem_bytes(self) -> int:
        return sum(p.mem_bytes for p in self.partitions.values())


def _round_up(n: int, to: int) -> int:
    return ((n + to - 1) // to) * to if to else n
