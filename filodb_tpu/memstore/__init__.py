"""In-memory time-series store: the reference's memstore layer rebuilt
host-side, feeding device-ready batches to the TPU query kernels
(reference: core/src/main/scala/filodb.core/memstore/)."""

from filodb_tpu.memstore.index import PartKeyIndex
from filodb_tpu.memstore.partition import TimeSeriesPartition
from filodb_tpu.memstore.shard import TimeSeriesShard
from filodb_tpu.memstore.memstore import TimeSeriesMemStore

__all__ = ["PartKeyIndex", "TimeSeriesPartition", "TimeSeriesShard",
           "TimeSeriesMemStore"]
