"""Cardinality explorer: who owns the series, and how fast they churn.

The reference answers "which label is blowing up my index" with offline
cardinality-busting jobs walking the Lucene index (reference:
spark-jobs cardinality busting; CardinalityManager reading counts off
PartKeyLuceneIndex).  Here the part-key index already maintains
per-value alive refcounts (:meth:`PartKeyIndex.cardinality_snapshot`),
so the explorer is an O(values) read, not a document walk:

- :class:`CardinalityTracker` rides each shard: churn counters + EWMA
  creation/removal rates noted at part-id assignment and evict/purge,
  plus ``set_fn``-sampled active-series/label gauges
  (``filodb_index_cardinality_*`` / ``filodb_index_churn_*``);
- :func:`build_report` assembles the ``/admin/cardinality`` payload —
  per-shard top-k label names x values by active-series count and the
  per-tenant breakdown — from one atomic index snapshot per shard, so
  the report's totals reconcile exactly with a full index walk even
  under concurrent create/evict/purge (asserted in
  tests/test_dataplane.py, the PR 9 ledger-style guarantee).

The per-tenant breakdown follows SeriesQuota semantics
(workload/quota.py): tenant = value of the tenant label (default
``_ns_``), series lacking the label pool under ``""``.
"""

from __future__ import annotations

import math
import threading
import time
import weakref
from typing import Optional, Sequence

_METRICS = None


def _m() -> dict:
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import index_metrics
        _METRICS = index_metrics()
    return _METRICS


class Ewma:
    """Exponentially-decayed event-rate estimator (events/second).

    ``note(n)`` adds n events; ``rate()`` reads the decayed rate.  For a
    steady stream of r events/s the estimate converges to r with a half
    life of ``halflife_s`` — the cheap, lock-tiny churn signal the
    explorer exports (a counter alone cannot alert on "creation rate
    spiked" without server-side rate())."""

    __slots__ = ("_tau", "_rate", "_t", "_lock")

    def __init__(self, halflife_s: float = 60.0):
        self._tau = max(halflife_s, 1e-3) / math.log(2)
        self._rate = 0.0
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _decay_locked(self, now: float) -> None:
        dt = now - self._t
        if dt > 0:
            self._rate *= math.exp(-dt / self._tau)
            self._t = now

    def note(self, n: float = 1.0, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._decay_locked(now)
            self._rate += n / self._tau

    def rate(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._decay_locked(now)
            return self._rate


class CardinalityTracker:
    """Per-shard churn accounting + cardinality gauges.

    The shard calls :meth:`note_created` right after assigning a NEW
    part id and :meth:`note_removed` on evict/purge; both are O(1)
    (one counter inc + one EWMA note).  Gauges are ``set_fn``-sampled
    at scrape time through a weakref to the index, so a dropped shard
    stops exporting instead of pinning the index alive."""

    def __init__(self, dataset: str, shard_num: int,
                 churn_halflife_s: float = 60.0):
        self.dataset = dataset
        self.shard_num = shard_num
        self.created_total = 0
        self.removed_total = 0
        self.create_ewma = Ewma(churn_halflife_s)
        self.remove_ewma = Ewma(churn_halflife_s)
        self._index_ref = None
        m = _m()
        labels = {"dataset": dataset, "shard": shard_num}
        m["create_rate"].set_fn(self.create_ewma.rate, **labels)
        m["remove_rate"].set_fn(self.remove_ewma.rate, **labels)

    def attach_index(self, index) -> None:
        """Register the set_fn cardinality gauges against this index."""
        self._index_ref = weakref.ref(index)
        ref = self._index_ref
        m = _m()
        labels = {"dataset": self.dataset, "shard": self.shard_num}
        m["active_series"].set_fn(
            lambda: float(idx.active_series_count())
            if (idx := ref()) is not None else 0.0, **labels)
        m["labels"].set_fn(
            lambda: float(len(idx.label_names()))
            if (idx := ref()) is not None else 0.0, **labels)

    def close(self) -> None:
        """Deregister every gauge this tracker owns (the Gauge.remove
        contract: set_fn registrants must remove on teardown or the
        registry exports dead-instance rows and pins the callbacks
        forever).  Driven by TimeSeriesMemStore.reset(); a tracker
        recreated under the same (dataset, shard) key simply replaces
        these registrations, so double-close/re-register is safe."""
        m = _m()
        labels = {"dataset": self.dataset, "shard": self.shard_num}
        for name in ("create_rate", "remove_rate", "active_series",
                     "labels"):
            m[name].remove(**labels)

    # ------------------------------------------------------------- churn

    def note_created(self, n: int = 1) -> None:
        self.created_total += n
        self.create_ewma.note(n)
        _m()["created"].inc(n, dataset=self.dataset, shard=self.shard_num)

    def note_removed(self, reason: str, n: int = 1) -> None:
        self.removed_total += n
        self.remove_ewma.note(n)
        _m()["removed"].inc(n, dataset=self.dataset, shard=self.shard_num,
                            reason=reason)

    def churn(self) -> dict:
        return {
            "created_total": self.created_total,
            "removed_total": self.removed_total,
            "create_rate_per_s": round(self.create_ewma.rate(), 6),
            "remove_rate_per_s": round(self.remove_ewma.rate(), 6),
        }


# tenants whose gauge rows are currently exported, per dataset: a
# tenant whose series all evicted must have its row REMOVED, or
# /metrics keeps reporting its last nonzero count forever
_EXPORTED_TENANTS: dict[str, set] = {}
_EXPORT_LOCK = threading.Lock()


def _set_tenant_gauges(dataset: str, merged: dict[str, int]) -> None:
    # set + stale-compute + remove all under ONE lock: the watermark
    # sampler and an inline /admin/cardinality refresh run concurrently,
    # and an unsynchronized interleaving could remove a row the other
    # pass just set — or record an exported-set that never contained a
    # now-dead tenant, leaking its last count forever
    gauge = _m()["tenant_series"]
    with _EXPORT_LOCK:
        for tenant, n in merged.items():
            gauge.set(n, dataset=dataset, tenant=tenant)
        stale = _EXPORTED_TENANTS.get(dataset, set()) - set(merged)
        _EXPORTED_TENANTS[dataset] = set(merged)  # filolint: disable=bounded-cache — keyed by dataset name; per-dataset sets shed drained tenants above
        for tenant in stale:
            gauge.remove(dataset=dataset, tenant=tenant)


def sample_tenant_gauges(dataset: str, shards: Sequence,
                         tenant_label: str = "_ns_") -> dict[str, int]:
    """Refresh ``filodb_index_cardinality_tenant_series`` from the
    shards' per-value refcounts and return the merged per-tenant counts
    (the watermark sampler drives this periodically; /admin/cardinality
    recomputes inline).  Uses ``value_counts(tenant_label)`` — O(tenant
    values) under the index lock — NOT the full snapshot: a sampler
    pass must never copy a million-series index's whole label map."""
    merged: dict[str, int] = {}
    for sh in shards:
        vc = sh.index.value_counts(tenant_label)
        for tenant, n in vc.items():
            merged[tenant] = merged.get(tenant, 0) + n
        untagged = sh.index.active_series_count() - sum(vc.values())
        if untagged > 0:
            merged[""] = merged.get("", 0) + untagged
    _set_tenant_gauges(dataset, merged)
    return merged


def _shard_report(sh, topk: int, tenant_label: str) -> tuple[dict, dict]:
    """One shard's explorer row + its per-tenant counts, all derived
    from a SINGLE atomic index snapshot (mutual consistency under
    concurrent churn)."""
    active, labels = sh.index.cardinality_snapshot()
    rows = []
    for name, vc in labels.items():
        rows.append({"label": name, "values": len(vc),
                     "series": sum(vc.values())})
    # labels ranked by distinct-value count — the axis that blows up an
    # index — then by covered series
    rows.sort(key=lambda r: (-r["values"], -r["series"], r["label"]))
    top_labels = []
    for r in rows[:topk]:
        vc = labels[r["label"]]
        top_values = sorted(vc.items(), key=lambda kv: (-kv[1], kv[0]))
        r["top_values"] = [{"value": v, "series": n}
                           for v, n in top_values[:topk]]
        top_labels.append(r)
    tvc = labels.get(tenant_label, {})
    tenants = dict(tvc)
    untagged = active - sum(tvc.values())
    if untagged > 0:
        tenants[""] = tenants.get("", 0) + untagged
    tracker = getattr(sh, "cardinality", None)
    row = {
        "shard": sh.shard_num,
        "active_series": active,
        "labels": len(labels),
        "top_labels": top_labels,
        "tenants": tenants,
        "churn": tracker.churn() if tracker is not None else {},
    }
    return row, tenants


def build_report(dataset: str, shards: Sequence, topk: int = 10,
                 tenant_label: str = "_ns_",
                 shard_num: Optional[int] = None) -> dict:
    """The ``/admin/cardinality`` payload (also the ``cardinality-report``
    CLI verb's body).  ``total_active_series`` is the sum of per-shard
    atomic snapshots; per-tenant counts merge the same snapshots, so
    ``sum(tenants.values()) == total_active_series`` holds exactly."""
    rows = []
    tenants: dict[str, int] = {}
    for sh in shards:
        if shard_num is not None and sh.shard_num != shard_num:
            continue
        row, sh_tenants = _shard_report(sh, topk, tenant_label)
        rows.append(row)
        for t, n in sh_tenants.items():
            tenants[t] = tenants.get(t, 0) + n
    if shard_num is None:
        # refresh the tenant gauges from the SAME numbers the report
        # shows — but only for FULL reports: a shard-filtered view must
        # not clobber the fleet-wide counts on /metrics
        _set_tenant_gauges(dataset, tenants)
    return {
        "dataset": dataset,
        "tenant_label": tenant_label,
        "topk": topk,
        "total_active_series": sum(r["active_series"] for r in rows),
        "tenants": tenants,
        "shards": rows,
    }
