"""Pipelined flush scheduling: time boundaries + a dedicated executor.

The reference never flushes on the ingest thread: ``createFlushTasks``
(ingest thread) detects per-group time-boundary crossings and snapshots
buffers; ``doFlushSteps`` encodes and writes on a separate flush
scheduler with ``flush-task-parallelism`` workers (reference:
core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:804-846,
TimeSeriesMemStore.scala:106-129).  This module is that split for the
TPU build: :class:`FlushScheduler` watches the shard's newest sample
timestamp, and when group *g*'s staggered boundary is crossed it runs
``shard.prepare_flush_group(g)`` inline (O(1) buffer detaches) and
submits ``shard.run_flush_task`` (encode + IO) to a thread pool.

Group boundaries are staggered across the flush interval — group g
flushes at phase ``g/G`` of each interval — so flush load spreads evenly
instead of spiking (reference :804-846).  Tasks for one group are
chained so they execute in submission order (checkpoint monotonicity);
different groups flush in parallel.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

_METRICS = None


def _m() -> dict:
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import flush_metrics
        _METRICS = flush_metrics()
    return _METRICS


class FlushScheduler:
    """Drives pipelined flushes for one shard.

    ``note_ingested()`` is called from the ingest thread after each
    container; it is O(1) when no boundary was crossed.  ``close()``
    drains all in-flight flush tasks.

    Observability (ISSUE 6 satellite): per-group pending-task depth and
    last-flush age are tracked here and exported as
    ``filodb_flush_queue_depth`` / ``filodb_flush_last_age_seconds``
    (set_fn-sampled; deregistered on close so dead schedulers leave no
    rows) plus a per-group :meth:`snapshot` for ``/admin/shards``.
    """

    def __init__(self, shard, flush_interval_ms: Optional[int] = None,
                 parallelism: int = 2):
        self.shard = shard
        self.interval = flush_interval_ms or shard.config.flush_interval_ms
        if self.interval <= 0:
            raise ValueError("flush interval must be positive")
        self.parallelism = parallelism
        self._exec = ThreadPoolExecutor(
            max_workers=parallelism,
            thread_name_prefix=f"flush-{shard.dataset}-{shard.shard_num}")
        ngroups = shard.num_groups
        # group g's boundary phase within each interval
        self._phase = [g * self.interval // ngroups for g in range(ngroups)]
        self._next_boundary: list[Optional[int]] = [None] * ngroups
        self._chains: dict[int, Future] = {}
        self._lock = threading.Lock()
        self.flushes_submitted = 0
        self._closed = False
        # submitted-but-not-completed per group + completion stamps
        self._pending = [0] * ngroups
        self._last_done: list[Optional[float]] = [None] * ngroups
        self._started_s = time.monotonic()
        self._labels = {"dataset": shard.dataset, "shard": shard.shard_num}
        m = _m()
        m["queue_depth"].set_fn(lambda: float(self.queue_depth()),
                                **self._labels)
        m["last_age"].set_fn(self.last_flush_age_s, **self._labels)

    # ------------------------------------------------------- observability

    def queue_depth(self) -> int:
        """Flush tasks submitted but not yet completed, all groups."""
        with self._lock:
            return sum(self._pending)

    def last_flush_age_s(self) -> float:
        """Seconds since the most recent completed flush on ANY group
        (age since scheduler start when nothing completed yet)."""
        with self._lock:
            done = [t for t in self._last_done if t is not None]
            anchor = max(done) if done else self._started_s
        return max(0.0, time.monotonic() - anchor)

    def snapshot(self) -> dict:
        """Per-group pipeline state for the /admin/shards health tree."""
        now = time.monotonic()
        with self._lock:
            groups = [
                {"group": g, "pending": self._pending[g],
                 "last_flush_age_s":
                     round(now - self._last_done[g], 3)
                     if self._last_done[g] is not None else None}
                for g in range(self.shard.num_groups)]
            submitted = self.flushes_submitted
            pending = sum(self._pending)
        return {"pending": pending, "flushes_submitted": submitted,
                "groups": groups}

    def _track(self, group: int, fut: Future) -> None:
        """Count a submitted task until its future resolves.  Caller
        must NOT hold ``_lock``: a fast (or inline) future runs the done
        callback synchronously from ``add_done_callback``, which takes
        the lock again."""
        with self._lock:
            self._pending[group] += 1

        def done(_f, _g=group):
            with self._lock:
                self._pending[_g] -= 1
                self._last_done[_g] = time.monotonic()

        fut.add_done_callback(done)

    def _boundary_after(self, t: int, group: int) -> int:
        ph = self._phase[group]
        return ((t - ph) // self.interval + 1) * self.interval + ph

    def note_ingested(self) -> int:
        """Check boundary crossings against the shard's newest sample
        timestamp; prepare + submit any due groups.  Returns the number
        of flush tasks submitted."""
        t = self.shard.latest_ingest_ts
        if t < 0:
            return 0
        submitted = 0
        for g in range(self.shard.num_groups):
            nb = self._next_boundary[g]
            if nb is None:
                # first sight of data: schedule the next boundary
                self._next_boundary[g] = self._boundary_after(t, g)
                continue
            if t >= nb:
                self._next_boundary[g] = self._boundary_after(t, g)
                self._submit(g)
                submitted += 1
        return submitted

    def flush_now(self, group: Optional[int] = None) -> None:
        """Force a flush of one group (or all) through the pipeline."""
        groups = range(self.shard.num_groups) if group is None else (group,)
        for g in groups:
            self._submit(g)

    def _submit(self, group: int) -> Future:
        # closed check BEFORE prepare: prepare irreversibly detaches
        # buffers and the dirty-partkey set, which would be dropped if we
        # prepared first and then refused the submit
        with self._lock:
            if self._closed:
                raise RuntimeError("FlushScheduler is closed")
        task = self.shard.prepare_flush_group(group)

        def run(_prev: Optional[Future]) -> int:
            return self.shard.run_flush_task(task)

        fut: Optional[Future] = None
        with self._lock:
            if not self._closed:
                try:
                    prev = self._chains.get(group)
                    if prev is None:
                        fut = self._exec.submit(run, None)
                    else:
                        # chain: group tasks run in submission order even
                        # when the pool has spare workers (checkpoint
                        # monotonicity)
                        fut = Future()

                        def after(p, _task=task, _fut=fut):
                            try:
                                _fut.set_result(
                                    self.shard.run_flush_task(_task))
                            except BaseException as e:  # via the future
                                _fut.set_exception(e)

                        prev.add_done_callback(
                            lambda p: self._exec.submit(after, p))
                    self._chains[group] = fut  # filolint: disable=bounded-cache — keyed by flush group id, bounded by groups-per-shard
                    self.flushes_submitted += 1
                except RuntimeError:
                    fut = None  # executor shut down between check and submit
        if fut is not None:
            self._track(group, fut)
            return fut
        # closed (or shut down) after prepare irreversibly detached the
        # buffers: run inline, outside the lock, so the snapshot is never
        # lost; the flush succeeded, so report it as such
        fut = Future()
        fut.set_result(self.shard.run_flush_task(task))
        with self._lock:
            self.flushes_submitted += 1
        self._track(group, fut)
        return fut

    def drain(self) -> None:
        """Block until all submitted flush tasks completed."""
        while True:
            with self._lock:
                futs = list(self._chains.values())
            for f in futs:
                f.result()
            with self._lock:
                if all(f.done() for f in self._chains.values()):
                    return

    def close(self, flush_remaining: bool = True) -> None:
        """Drain, optionally flush whatever is still buffered, shut down.
        The executor is shut down even when a flush task failed — the
        task's exception still propagates to the caller."""
        try:
            if flush_remaining:
                self.flush_now()
            self.drain()
        finally:
            with self._lock:
                self._closed = True
            self._exec.shutdown(wait=True)
            # deregister the sampled gauges: a retired scheduler must not
            # keep exporting rows (or keep the shard alive via set_fn)
            m = _m()
            m["queue_depth"].remove(**self._labels)
            m["last_age"].remove(**self._labels)
