"""Part-key tag index: label -> value -> sorted numpy posting arrays.

Re-scoped inverted index with the feature set the reference gets from
Lucene (reference: core/src/main/scala/filodb.core/memstore/
PartKeyLuceneIndex.scala:70 — partIdsFromFilters, partIdsOrderedByEndTime,
startTimeFromPartIds, labelValues faceting, __startTime__/__endTime__
fields), deliberately not a Lucene port (SURVEY.md §7 "Deliberately not
ported").

Round-3 redesign for Lucene-class lookup throughput (VERDICT r2 weak #2 /
do-this #4 — the round-2 Python-set postings walked per-id dicts on every
lookup, ~150 ms cold at 1M series):

- postings are **sorted int32 numpy arrays** (append-buffered, merged
  lazily); per-value postings within one label are DISJOINT (a series
  carries one value per label), so unions are concat+sort with no
  dedup pass, and the result feeds batch gathers directly;
- each label also keeps a **dense pid -> value-code array** (the
  Lucene doc-values analog): a multi-filter lookup walks ONE base
  posting (the narrowest) and evaluates every other filter as a code
  gather + tiny value-table probe — no posting intersections at all;
- series lifetimes live in **dense numpy arrays** indexed by part id
  (ids are dense ints assigned by the shard), so the
  ``__endTime__ >= start && __startTime__ <= end`` clause is one
  vectorized mask instead of a per-id dict walk;
- regex filters match the label's *value dictionary*, never documents
  (the trick Lucene's RegexpQuery enables): one compiled regex runs
  over the newline-joined value corpus in a single C-level pass; the
  matched-value facet is memoized per (pattern, value generation) and
  the unioned posting per (pattern, label mutation counter), so
  repeated dashboard regexes skip both the matching and the sort;
- removals flip an ``alive`` bit and decrement per-value refcounts;
  postings are filtered by the alive mask at read time and fully
  compacted once removals exceed 25% of the index — amortized O(1).

Missing-label semantics follow ColumnFilter.matches (absent label reads
as ""): a filter that matches "" also selects series WITHOUT the label
(e.g. ``{a=~".*"}`` or ``{a!="x"}`` match series lacking ``a``).  Such
filters are never chosen as the base posting; as code predicates the
absent-label slot of the value table carries ``matches("")``, so the
semantics hold uniformly.
"""

from __future__ import annotations

import re
import threading
from typing import Iterable, Optional, Sequence

import numpy as np

from filodb_tpu.core.filters import (ColumnFilter, Equals, EqualsRegex, In,
                                     NotEquals, NotEqualsRegex, NotIn)

_NO_END = np.iinfo(np.int64).max
_EMPTY = np.empty(0, np.int32)
_EMPTY.setflags(write=False)


class _Posting:
    """Sorted int32 id array + append buffer.  Shard-assigned part ids
    are (near-)monotone, so merging the buffer is usually a concat."""

    __slots__ = ("arr", "pending")

    def __init__(self) -> None:
        self.arr = _EMPTY
        self.pending: list[int] = []

    def add(self, pid: int) -> None:
        self.pending.append(pid)

    def __len__(self) -> int:
        return len(self.arr) + len(self.pending)

    def ids(self) -> np.ndarray:
        if self.pending:
            tail = np.asarray(self.pending, np.int32)
            if len(tail) > 1 and (np.diff(tail) <= 0).any():
                tail = np.unique(tail)
            if len(self.arr) and len(tail) and self.arr[-1] >= tail[0]:
                merged = np.union1d(self.arr, tail).astype(np.int32)
            else:
                merged = np.concatenate([self.arr, tail])
            # lookups may return this array uncopied; a mutating caller
            # must fail loudly instead of corrupting the index
            merged.setflags(write=False)
            self.arr = merged
            self.pending.clear()
        return self.arr


# constructs whose line-wise corpus behavior DIFFERS from per-value
# fullmatch: absolute anchors only succeed at the corpus's own ends
# (missing matches on interior lines) and lookarounds can observe the
# joining newlines (spurious matches the value-dictionary guard can't
# catch, because they return real values for the wrong reason)
_CORPUS_UNSAFE = ("\\A", "\\Z", "\\z", "(?=", "(?!", "(?<")


def _corpus_unsafe(pattern: str) -> bool:
    return any(tok in pattern for tok in _CORPUS_UNSAFE)


class _Label:
    """All per-label state in one object (one dict hop on the hot
    ingest path): value postings, the dense pid->value-code array,
    per-value alive refcounts, and the regex corpus.

    ``codes`` is the Lucene-doc-values analog that makes multi-filter
    lookups O(base posting): any additional filter on another label is
    ONE gather of that label's codes plus a tiny value-table probe —
    no posting intersection at all."""

    __slots__ = ("by_val", "vcount", "code_of", "codes", "vgen",
                 "gen", "_corpus", "_regex_memo", "_union_memo")

    def __init__(self) -> None:
        self.by_val: dict[str, _Posting] = {}
        self.vcount: dict[str, int] = {}
        self.code_of: dict[str, int] = {}
        self.codes = np.full(1024, -1, np.int32)   # pid -> code; -1 absent
        self.vgen = 0          # bumps when a NEW value appears
        self.gen = 0           # bumps on EVERY add (union memo key)
        self._corpus: Optional[tuple[int, str, list[str]]] = None
        self._regex_memo: dict[str, tuple[int, list[str]]] = {}
        # regex -> (gen, sorted union ids): repeated dashboard regexes
        # skip the concat+sort while the label is unchanged
        self._union_memo: dict[str, tuple[int, np.ndarray]] = {}

    def ensure(self, n: int) -> None:
        if n <= len(self.codes):
            return
        new = np.full(max(n, len(self.codes) * 2), -1, np.int32)
        new[:len(self.codes)] = self.codes
        self.codes = new

    def add(self, v: str, pid: int) -> None:
        p = self.by_val.get(v)
        if p is None:
            p = self.by_val[v] = _Posting()  # filolint: disable=bounded-cache — the index IS the data; cardinality is bounded by the series-quota subsystem
            self.code_of[v] = self.vgen  # filolint: disable=bounded-cache — index value-code table, same bound as by_val
            self.vgen += 1
        # inlined _Posting.add: this runs once per (series, label)
        p.pending.append(pid)
        self.vcount[v] = self.vcount.get(v, 0) + 1  # filolint: disable=bounded-cache — index refcounts, same bound as by_val
        self.gen += 1
        if pid >= len(self.codes):
            self.ensure(pid + 1)
        self.codes[pid] = self.code_of[v]

    def add_many(self, pairs: list[tuple[str, int]]) -> None:
        """Batched :meth:`add` (the deferred-apply path): one ensure,
        one vectorized code scatter, Counter-merged value counts."""
        from collections import Counter
        by_val = self.by_val
        code_of = self.code_of
        self.ensure(max(pid for _v, pid in pairs) + 1)
        code_list: list[int] = []
        for v, pid in pairs:
            p = by_val.get(v)
            if p is None:
                p = by_val[v] = _Posting()
                code_of[v] = self.vgen
                self.vgen += 1
            p.pending.append(pid)
            code_list.append(code_of[v])
        self.codes[np.fromiter((pid for _v, pid in pairs), np.int64,
                               len(pairs))] = \
            np.asarray(code_list, np.int32)
        vcount = self.vcount
        for v, c in Counter(v for v, _pid in pairs).items():
            vcount[v] = vcount.get(v, 0) + c
        self.gen += len(pairs)

    def matching_values(self, flt) -> list[str]:
        """Values of this label matching a regex filter, via one pass of
        the compiled pattern over the newline-joined value corpus;
        memoized per (pattern, value generation)."""
        memo = self._regex_memo.get(flt.pattern)
        if memo is not None and memo[0] == self.vgen:
            return memo[1]
        if self._corpus is None or self._corpus[0] != self.vgen:
            vals = list(self.by_val.keys())
            if any("\n" in v for v in vals):
                self._corpus = (self.vgen, "", vals)   # corpus unusable
            else:
                self._corpus = (self.vgen, "\n".join(vals), vals)
        _, joined, vals = self._corpus
        if (joined == "" and len(vals) > 1) or _corpus_unsafe(flt.pattern):
            out = [v for v in vals if flt.matches(v)]
        else:
            try:
                rx = re.compile(rf"(?m)^(?:{flt.pattern})$")
                out = rx.findall(joined) if len(vals) > 1 else \
                    [v for v in vals if flt.matches(v)]
                # fall back to per-value matching when the corpus trick
                # is unsound: patterns with a capture group (findall
                # returns group contents) and patterns that can match
                # newlines (e.g. [\s\S]*) whose matches span adjacent
                # corpus lines — detectable as results that are not
                # actual dictionary values
                if rx.groups or any(v not in self.by_val for v in out):
                    out = [v for v in vals if flt.matches(v)]
            except re.error:
                out = [v for v in vals if flt.matches(v)]
        if len(self._regex_memo) > 256:
            self._regex_memo.clear()
        self._regex_memo[flt.pattern] = (self.vgen, out)
        return out


class PartKeyIndex:
    """One index per shard; partition ids are dense ints assigned by the shard."""

    def __init__(self, auto_apply: bool = True) -> None:
        # auto_apply=False suppresses the background applier (bulk
        # loads / benches that drain explicitly via apply_pending)
        self._auto_apply = auto_apply
        self._labels: dict[str, _Label] = {}
        self._tags: dict[int, dict[str, str]] = {}
        self._partkeys: dict[int, bytes] = {}
        # dense per-pid arrays, grown by doubling
        self._start_arr = np.zeros(1024, np.int64)
        self._end_arr = np.full(1024, _NO_END, np.int64)
        self._alive = np.zeros(1024, bool)
        self._max_pid = -1
        self._removed = 0
        # ONE lock serializes writers with the lazy structures reads
        # materialize (posting pending-merges, code-array growth, memo
        # fills): reads MUTATE shared state in this design, unlike the
        # copy-on-read set postings it replaced, so the single-writer /
        # many-reader shard discipline alone is not enough
        self._lock = threading.Lock()
        # monotone mutation counter: lookup caches key on it so repeated
        # dashboard filters skip the postings walk until the index changes
        self.version = 0
        # DEFERRED label writes (reference: PartKeyLuceneIndex.scala:151
        # — documents land on a background Lucene flush thread, not the
        # ingest path): add_partkey records only the O(1) lifetime state
        # and queues the posting/value-code work; an applier thread (or
        # the next lookup) drains it under the same lock
        self._pending_adds: list[tuple[int, dict]] = []
        self._pending_cv = threading.Condition(self._lock)
        self._applier_alive = False

    def __len__(self) -> int:
        return len(self._tags)

    # -- write path ---------------------------------------------------------

    def _grow(self, pid: int) -> None:
        n = len(self._start_arr)
        if pid < n:
            return
        m = max(n * 2, pid + 1)
        for name, fill in (("_start_arr", 0), ("_end_arr", _NO_END),
                           ("_alive", False)):
            old = getattr(self, name)
            new = np.full(m, fill, old.dtype)
            new[:n] = old
            setattr(self, name, new)

    def add_partkey(self, part_id: int, partkey: bytes, tags: dict[str, str],
                    start_time: int, end_time: int = _NO_END) -> None:
        """INGEST-THREAD cost is O(1): lifetime arrays + tag/partkey maps
        are written immediately (the ingest path reads them right back);
        the per-label posting/value-code writes — the expensive part —
        are queued for the applier thread / next lookup."""
        with self._lock:
            self.version += 1
            self._grow(part_id)
            self._tags[part_id] = tags
            self._partkeys[part_id] = partkey
            self._start_arr[part_id] = start_time
            self._end_arr[part_id] = end_time
            self._alive[part_id] = True
            if part_id > self._max_pid:
                self._max_pid = part_id
            self._pending_adds.append((part_id, tags))
            n = len(self._pending_adds)
            if n > 256 and not self._applier_alive and self._auto_apply:
                # spawn lazily past a real backlog so short-lived test
                # indexes never pay a thread; exits again when idle
                self._applier_alive = True
                threading.Thread(target=self._applier_loop,
                                 name="pkindex-applier",
                                 daemon=True).start()
            if n & 1023 == 0:          # amortize the notify cost
                self._pending_cv.notify()

    def _apply_chunk_locked(self, chunk) -> None:
        labels = self._labels
        tags_map = self._tags
        per_label: dict[str, list] = {}
        for pid, tags in chunk:
            if tags_map.get(pid) is not tags:
                continue       # removed/replaced before its labels landed
            for k, v in tags.items():
                lst = per_label.get(k)
                if lst is None:
                    lst = per_label[k] = []
                lst.append((v, pid))
        for k, pairs in per_label.items():
            lab = labels.get(k)
            if lab is None:
                lab = labels[k] = _Label()
            lab.add_many(pairs)

    def _drain_pending_locked(self) -> None:
        """Apply EVERY queued label write; caller holds the lock.  Every
        posting/label read path runs this first, so lookups always see
        the full index regardless of applier progress."""
        if self._pending_adds:
            chunk = self._pending_adds
            self._pending_adds = []
            self._apply_chunk_locked(chunk)

    def apply_pending(self) -> None:
        """Drain queued label writes now (flush-executor hook; tests)."""
        with self._lock:
            self._drain_pending_locked()

    def _applier_loop(self) -> None:
        """Background writer (the Lucene flush-thread analog): drains in
        bounded chunks so a 1M-series burst never starves the ingest
        thread on the lock; exits after sustained idleness."""
        idle = 0
        while True:
            with self._pending_cv:
                if not self._pending_adds:
                    if not self._pending_cv.wait(timeout=5.0):
                        idle += 1
                        if idle >= 6:          # ~30s idle: retire
                            self._applier_alive = False
                            return
                        continue
                idle = 0
                chunk = self._pending_adds[:8192]
                del self._pending_adds[:8192]
                self._apply_chunk_locked(chunk)

    def update_end_time(self, part_id: int, end_time: int) -> None:
        """Marks a series stopped (reference: updatePartKeyWithEndTime, used
        by flush step updateIndexWithEndTime and by eviction ordering).
        Locked: a concurrent add_partkey _grow would otherwise strand
        this write in the superseded array."""
        with self._lock:
            if self._end_arr[part_id] != end_time:
                self.version += 1
            self._end_arr[part_id] = end_time

    def mark_active(self, part_id: int) -> None:
        with self._lock:
            if self._end_arr[part_id] != _NO_END:
                self.version += 1
            self._end_arr[part_id] = _NO_END

    def remove(self, part_ids: Iterable[int]) -> None:
        with self._lock:
            self._remove_locked(part_ids)

    def _remove_locked(self, part_ids) -> None:
        # settle queued label writes first: a pending add for a pid we
        # are about to remove would otherwise land AFTER the removal
        # (ghost postings), and _compact rebuilding from _tags would
        # double-apply whatever is still queued
        self._drain_pending_locked()
        self.version += 1
        for pid in part_ids:
            tags = self._tags.pop(pid, None)
            if tags is None:
                continue
            self._partkeys.pop(pid, None)
            self._alive[pid] = False
            self._end_arr[pid] = _NO_END
            self._removed += 1
            for k, v in tags.items():
                lab = self._labels.get(k)
                if lab is not None and v in lab.vcount:
                    lab.vcount[v] -= 1
                    if lab.vcount[v] <= 0:
                        del lab.vcount[v]
        if self._removed * 4 > max(len(self._tags), 64):
            self._compact()

    def _compact(self) -> None:
        """Rebuild postings from live tags, dropping dead ids.  Runs once
        per ~25% turnover, so the per-remove cost stays amortized O(1)."""
        self._labels.clear()
        self._removed = 0
        for pid in sorted(self._tags):
            for k, v in self._tags[pid].items():
                lab = self._labels.get(k)
                if lab is None:
                    lab = self._labels[k] = _Label()
                lab.add(v, pid)

    # -- read path ----------------------------------------------------------

    def _live(self, ids: np.ndarray) -> np.ndarray:
        if self._removed == 0 or len(ids) == 0:
            return ids
        return ids[self._alive[ids]]

    def _all_ids(self) -> np.ndarray:
        ids = np.flatnonzero(self._alive[:self._max_pid + 1])
        return ids.astype(np.int32)

    def _value_posting(self, column: str, value: str) -> np.ndarray:
        lab = self._labels.get(column)
        if lab is None:
            return _EMPTY
        p = lab.by_val.get(value)
        return p.ids() if p is not None else _EMPTY

    def _union(self, column: str, values: Iterable[str]) -> np.ndarray:
        """Union of one label's value postings.  A series carries ONE
        value per label, so the postings are disjoint: concat + sort,
        no dedup pass."""
        parts = [self._value_posting(column, v) for v in values]
        parts = [p for p in parts if len(p)]
        if not parts:
            return _EMPTY
        if len(parts) == 1:
            return parts[0]
        return np.sort(np.concatenate(parts))

    def _base_size(self, f: ColumnFilter) -> Optional[int]:
        """Result-size estimate when this positive filter is served from
        postings; None = not usable as the base (negative filters, and
        filters matching "" — those also select series WITHOUT the
        label, which only the code predicate handles)."""
        flt = f.filter
        lab = self._labels.get(f.column)
        if isinstance(flt, Equals):
            if flt.value == "":
                return None
            if lab is None:
                return 0
            p = lab.by_val.get(flt.value)
            return len(p) if p is not None else 0
        if isinstance(flt, In):
            if "" in flt.values:
                return None
            if lab is None:
                return 0
            return sum(len(p) for v in flt.values
                       if (p := lab.by_val.get(v)) is not None)
        if isinstance(flt, EqualsRegex):
            if flt.matches(""):
                return None
            if lab is None:
                return 0
            return sum(len(lab.by_val[v]) for v in lab.matching_values(flt))
        return None

    def _base_ids(self, f: ColumnFilter) -> np.ndarray:
        flt = f.filter
        if isinstance(flt, Equals):
            return self._value_posting(f.column, flt.value)
        if isinstance(flt, In):
            return self._union(f.column, flt.values)
        lab = self._labels.get(f.column)
        if lab is None:
            return _EMPTY
        memo = lab._union_memo.get(flt.pattern)
        if memo is not None and memo[0] == lab.gen:
            return memo[1]
        out = self._union(f.column, lab.matching_values(flt))
        if out.flags.writeable:        # same fail-loudly guard as postings
            out = out.copy()
            out.setflags(write=False)
        if len(lab._union_memo) > 64:
            lab._union_memo.clear()
        lab._union_memo[flt.pattern] = (lab.gen, out)
        return out

    def _predicate(self, f: ColumnFilter, ids64: np.ndarray) -> np.ndarray:
        """Boolean mask of ``ids64`` satisfying the filter, via one
        gather of the label's code array + a value-table probe.  Codes
        are shifted by +1 so slot 0 is 'label absent', which matches
        the filter against "" (ColumnFilter.matches semantics)."""
        flt = f.filter
        lab = self._labels.get(f.column)
        if lab is None:
            # label absent everywhere: every id reads ""
            return np.full(len(ids64), flt.matches(""), bool)
        lab.ensure(self._max_pid + 1)
        sh = lab.codes.take(ids64) + 1
        table = np.zeros(lab.vgen + 1, bool)
        table[0] = flt.matches("")
        if isinstance(flt, Equals):
            c = lab.code_of.get(flt.value)
            if c is not None:
                table[c + 1] = True
        elif isinstance(flt, In):
            for v in flt.values:
                c = lab.code_of.get(v)
                if c is not None:
                    table[c + 1] = True
        elif isinstance(flt, EqualsRegex):
            for v in lab.matching_values(flt):
                table[lab.code_of[v] + 1] = True
        elif isinstance(flt, (NotEquals, NotIn, NotEqualsRegex)):
            table[1:] = True
            if isinstance(flt, NotEquals):
                bad = (flt.value,)
            elif isinstance(flt, NotIn):
                bad = flt.values
            else:     # values the PATTERN matches fail the negation;
                      # reuses the memoized positive-regex facet
                bad = lab.matching_values(EqualsRegex(flt.pattern))
            for v in bad:
                c = lab.code_of.get(v)
                if c is not None:
                    table[c + 1] = False
        else:
            # unknown filter type: per-id fallback keeps semantics
            return np.fromiter(
                (f.matches(self._tags.get(int(pid), {})) for pid in ids64),
                bool, count=len(ids64))
        return table.take(sh)

    def _candidate_ids(self, filters: Sequence[ColumnFilter]) -> np.ndarray:
        """Sorted alive ids matching all filters (no time clause):
        narrowest usable posting as the base, every other filter a
        code-gather predicate over it."""
        base = None
        base_est = None
        for f in filters:
            est = self._base_size(f)
            if est is not None and (base_est is None or est < base_est):
                base, base_est = f, est
        if base is not None:
            if base_est == 0:
                return _EMPTY
            ids = self._live(np.asarray(self._base_ids(base), np.int32))
        else:
            ids = self._all_ids()
        rest = [f for f in filters if f is not base]
        if rest and len(ids):
            ids64 = ids.astype(np.int64)
            keep = None
            for f in rest:
                m = self._predicate(f, ids64)
                keep = m if keep is None else keep & m
            if not keep.all():
                ids = ids[keep]
        return np.asarray(ids, np.int32)

    def part_ids_from_filters(self, filters: Sequence[ColumnFilter],
                              start_time: int = 0,
                              end_time: int = _NO_END,
                              limit: Optional[int] = None) -> np.ndarray:
        """Sorted part ids whose tags match all filters and whose [start,end]
        life overlaps the query range (reference: partIdsFromFilters +
        __endTime__ >= start && __startTime__ <= end clauses)."""
        with self._lock:
            self._drain_pending_locked()
            ids = self._candidate_ids(filters)
        if len(ids):
            # .take with a pre-cast int64 index is ~2x a plain fancy
            # index here; this pair of gathers bounds wide lookups
            idx64 = ids.astype(np.int64)
            mask = (self._end_arr.take(idx64) >= start_time) & \
                (self._start_arr.take(idx64) <= end_time)
            if not mask.all():
                ids = ids[mask]
        if limit is not None:
            ids = ids[:limit]
        return ids

    def part_ids_ordered_by_end_time(self, n: int,
                                     before: int = _NO_END) -> list[int]:
        """Oldest-ending (stopped-longest-ago) partitions first — the
        eviction ordering (reference: partIdsOrderedByEndTime,
        TimeSeriesShard eviction :1308-1401)."""
        ids = self._all_ids()
        ends = self._end_arr[ids]
        sel = ends < before
        ids, ends = ids[sel], ends[sel]
        order = np.argsort(ends, kind="stable")[:n]
        return [int(i) for i in ids[order]]

    def start_time(self, part_id: int) -> int:
        if part_id not in self._tags:
            raise KeyError(part_id)
        return int(self._start_arr[part_id])

    def end_time(self, part_id: int) -> int:
        if part_id not in self._tags:
            raise KeyError(part_id)
        return int(self._end_arr[part_id])

    def tags(self, part_id: int) -> dict[str, str]:
        return self._tags[part_id]

    def partkey(self, part_id: int) -> bytes:
        return self._partkeys[part_id]

    def label_names(self, filters: Sequence[ColumnFilter] = (),
                    start_time: int = 0, end_time: int = _NO_END) -> list[str]:
        if not filters:
            # writers mutate _labels / vcount under _lock; snapshot under
            # it so a concurrent add_partkey can't resize mid-iteration
            with self._lock:
                self._drain_pending_locked()
                return sorted(k for k, lab in list(self._labels.items())
                              if lab.vcount)
        names: set[str] = set()
        for pid in self.part_ids_from_filters(filters, start_time, end_time):
            names.update(self._tags[int(pid)].keys())
        return sorted(names)

    def active_series_count(self) -> int:
        """Series currently alive in this index (the cardinality the
        quota subsystem caps; reference: CardinalityManager reading
        counts off the part-key index)."""
        return len(self._tags)

    def cardinality_snapshot(self) -> tuple[int, dict[str, dict[str, int]]]:
        """``(active_series, {label: {value: alive_count}})`` taken in
        ONE lock acquisition (pending label writes drained first), so
        every number in the snapshot is mutually consistent even while
        concurrent create/evict/purge churn the index — the
        reconciliation guarantee /admin/cardinality is built on
        (reference: the offline cardinality-buster jobs walk the Lucene
        index; here the per-value alive refcounts ARE that walk)."""
        with self._lock:
            self._drain_pending_locked()
            labels = {}
            for k, lab in self._labels.items():
                d = {v: n for v, n in lab.vcount.items() if n > 0}
                if d:
                    labels[k] = d
            return len(self._tags), labels

    def value_counts(self, label: str) -> dict[str, int]:
        """Alive-series count per value of one label, O(values): the
        per-value refcounts ARE the active cardinality breakdown — the
        workload quota's ground truth (workload/quota.py
        refresh_from_index), no document walk."""
        with self._lock:
            self._drain_pending_locked()
            lab = self._labels.get(label)
            if lab is None:
                return {}
            return {v: n for v, n in lab.vcount.items() if n > 0}

    def label_values(self, label: str, filters: Sequence[ColumnFilter] = (),
                     start_time: int = 0, end_time: int = _NO_END,
                     limit: Optional[int] = None) -> list[str]:
        """Distinct values of one label (reference: labelValuesEfficient
        faceting when unfiltered; filtered path scans matching docs)."""
        if not filters:
            with self._lock:
                self._drain_pending_locked()
                lab = self._labels.get(label)
                out = sorted(lab.vcount.keys()) if lab is not None else []
        else:
            vals: set[str] = set()
            for pid in self.part_ids_from_filters(filters, start_time, end_time):
                v = self._tags[int(pid)].get(label)
                if v is not None:
                    vals.add(v)
            out = sorted(vals)
        return out[:limit] if limit is not None else out
