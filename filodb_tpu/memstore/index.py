"""Part-key tag index: label -> value -> posting set of partition ids.

Re-scoped inverted index with the feature set the reference gets from
Lucene (reference: core/src/main/scala/filodb.core/memstore/
PartKeyLuceneIndex.scala:70 — partIdsFromFilters, partIdsOrderedByEndTime,
startTimeFromPartIds, labelValues faceting, __startTime__/__endTime__
fields), deliberately not a Lucene port (SURVEY.md §7 "Deliberately not
ported").  Postings are Python sets on the ingest path; query-time
intersection works on sorted numpy arrays so the result feeds straight into
batch gathers.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from filodb_tpu.core.filters import (ColumnFilter, Equals, EqualsRegex, In,
                                     NotEquals, NotEqualsRegex, NotIn)

_NO_END = np.iinfo(np.int64).max


class PartKeyIndex:
    """One index per shard; partition ids are dense ints assigned by the shard."""

    def __init__(self) -> None:
        self._postings: dict[str, dict[str, set[int]]] = {}
        self._tags: dict[int, dict[str, str]] = {}
        self._partkeys: dict[int, bytes] = {}
        self._start: dict[int, int] = {}
        self._end: dict[int, int] = {}
        # monotone mutation counter: lookup caches key on it so repeated
        # dashboard filters skip the postings walk until the index changes
        self.version = 0

    def __len__(self) -> int:
        return len(self._tags)

    # -- write path ---------------------------------------------------------

    def add_partkey(self, part_id: int, partkey: bytes, tags: dict[str, str],
                    start_time: int, end_time: int = _NO_END) -> None:
        self.version += 1
        self._tags[part_id] = tags
        self._partkeys[part_id] = partkey
        self._start[part_id] = start_time
        self._end[part_id] = end_time
        for k, v in tags.items():
            self._postings.setdefault(k, {}).setdefault(v, set()).add(part_id)

    def update_end_time(self, part_id: int, end_time: int) -> None:
        """Marks a series stopped (reference: updatePartKeyWithEndTime, used
        by flush step updateIndexWithEndTime and by eviction ordering)."""
        if self._end.get(part_id) != end_time:
            self.version += 1
        self._end[part_id] = end_time

    def mark_active(self, part_id: int) -> None:
        if self._end.get(part_id) != _NO_END:
            self.version += 1
        self._end[part_id] = _NO_END

    def remove(self, part_ids: Iterable[int]) -> None:
        self.version += 1
        for pid in part_ids:
            tags = self._tags.pop(pid, None)
            if tags is None:
                continue
            self._partkeys.pop(pid, None)
            self._start.pop(pid, None)
            self._end.pop(pid, None)
            for k, v in tags.items():
                vals = self._postings.get(k)
                if vals is None:
                    continue
                s = vals.get(v)
                if s is not None:
                    s.discard(pid)
                    if not s:
                        del vals[v]

    # -- read path ----------------------------------------------------------

    def part_ids_from_filters(self, filters: Sequence[ColumnFilter],
                              start_time: int = 0,
                              end_time: int = _NO_END,
                              limit: Optional[int] = None) -> np.ndarray:
        """Sorted part ids whose tags match all filters and whose [start,end]
        life overlaps the query range (reference: partIdsFromFilters +
        __endTime__ >= start && __startTime__ <= end clauses)."""
        ids = self._candidate_ids(filters)
        out = np.fromiter(
            (pid for pid in ids
             if self._end.get(pid, _NO_END) >= start_time
             and self._start.get(pid, 0) <= end_time),
            dtype=np.int32)
        out.sort()
        if limit is not None:
            out = out[:limit]
        return out

    def _candidate_ids(self, filters: Sequence[ColumnFilter]) -> set[int]:
        positive: list[set[int]] = []
        negative: list[ColumnFilter] = []
        for f in filters:
            flt = f.filter
            vals = self._postings.get(f.column, {})
            if isinstance(flt, Equals):
                positive.append(vals.get(flt.value, set()))
            elif isinstance(flt, In):
                positive.append(set().union(*(vals.get(v, set()) for v in flt.values)))
            elif isinstance(flt, EqualsRegex):
                # faceted regex: match against the label's value dictionary,
                # not each document — same trick Lucene's RegexpQuery enables
                positive.append(set().union(
                    *(s for v, s in vals.items() if flt.matches(v))) if vals else set())
            else:
                negative.append(f)
        if positive:
            ids = set.intersection(*map(set, positive)) if len(positive) > 1 \
                else set(positive[0])
        else:
            ids = set(self._tags.keys())
        for f in negative:
            ids = {pid for pid in ids if f.matches(self._tags[pid])}
        return ids

    def part_ids_ordered_by_end_time(self, n: int,
                                     before: int = _NO_END) -> list[int]:
        """Oldest-ending (stopped-longest-ago) partitions first — the
        eviction ordering (reference: partIdsOrderedByEndTime,
        TimeSeriesShard eviction :1308-1401)."""
        stopped = [(e, pid) for pid, e in self._end.items() if e < before]
        stopped.sort()
        return [pid for _, pid in stopped[:n]]

    def start_time(self, part_id: int) -> int:
        return self._start[part_id]

    def end_time(self, part_id: int) -> int:
        return self._end[part_id]

    def tags(self, part_id: int) -> dict[str, str]:
        return self._tags[part_id]

    def partkey(self, part_id: int) -> bytes:
        return self._partkeys[part_id]

    def label_names(self, filters: Sequence[ColumnFilter] = (),
                    start_time: int = 0, end_time: int = _NO_END) -> list[str]:
        if not filters:
            return sorted(self._postings.keys())
        names: set[str] = set()
        for pid in self.part_ids_from_filters(filters, start_time, end_time):
            names.update(self._tags[int(pid)].keys())
        return sorted(names)

    def label_values(self, label: str, filters: Sequence[ColumnFilter] = (),
                     start_time: int = 0, end_time: int = _NO_END,
                     limit: Optional[int] = None) -> list[str]:
        """Distinct values of one label (reference: labelValuesEfficient
        faceting when unfiltered; filtered path scans matching docs)."""
        if not filters:
            out = sorted(self._postings.get(label, {}).keys())
        else:
            vals: set[str] = set()
            for pid in self.part_ids_from_filters(filters, start_time, end_time):
                v = self._tags[int(pid)].get(label)
                if v is not None:
                    vals.add(v)
            out = sorted(vals)
        return out[:limit] if limit is not None else out
