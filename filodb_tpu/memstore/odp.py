"""On-demand paging: serve queries for series whose chunks live only on disk.

Capability match for the reference's OnDemandPagingShard +
DemandPagedChunkStore (reference: core/src/main/scala/filodb.core/
memstore/OnDemandPagingShard.scala, DemandPagedChunkStore.scala:34): on
query, partitions found in the tag index but absent from memory (evicted,
or index-bootstrapped after restart) have their raw chunks read back from
the ColumnStore and re-materialized.  Paged partitions are read-only and
live in a bytes-bounded LRU cache — the stand-in for time-bucketed block
memory with reclaim-on-demand.  A paged partition always holds its FULL
persisted history (cache granularity is the partition), so repeated
queries at different ranges see consistent data.

Also enforces the per-query scanned-data cap over chunks overlapping the
query range (``StoreConfig.max_data_per_shard_query``; reference
capDataScannedPerShardCheck).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.record import parse_partkey
from filodb_tpu.memstore.partition import TimeSeriesPartition
from filodb_tpu.memstore.shard import PartLookupResult, TimeSeriesShard
from filodb_tpu.store.columnstore import PartKeyRecord

_MAX_TIME = 2**62


class QueryLimitExceeded(Exception):
    """A query would scan more bytes than max_data_per_shard_query allows."""


class _PagedPartitions:
    """Bytes-bounded LRU of read-only re-materialized partitions (int keys)
    and backfill chunk lists for live partitions (``("bf", pid)`` keys).

    All methods take an internal lock: ODP shards are queried concurrently
    from HTTP handler threads, so the OrderedDict reorder + byte accounting
    must not interleave."""

    def __init__(self, max_bytes: int, on_evict=None):
        self.max_bytes = max_bytes
        self._entries: OrderedDict = OrderedDict()   # key -> (value, nbytes)
        self._bytes = 0
        self._lock = threading.Lock()
        # called AFTER put releases the lock when LRU pressure dropped an
        # entry (deadlock-safe; implementations must not assume mutual
        # exclusion with concurrent put/get) — the ODP shard bumps its
        # removal epoch so grid plan memos referencing the evicted
        # partition revalidate
        self._on_evict = on_evict

    def get(self, key):
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            self._entries.move_to_end(key)
            return ent[0]

    def put(self, key, value, nbytes: int) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            evicted = False
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_ev, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                evicted = True
        if evicted and self._on_evict is not None:
            self._on_evict()

    def pop(self, key) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]

    def __len__(self) -> int:
        """Number of cached whole partitions (backfill entries excluded)."""
        with self._lock:
            return sum(1 for k in self._entries if isinstance(k, int))


class OnDemandPagingShard(TimeSeriesShard):
    """TimeSeriesShard that pages missing partitions from the ColumnStore."""

    def __init__(self, *args, page_cache_bytes: int = 256 * 1024 * 1024,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.paged = _PagedPartitions(page_cache_bytes,
                                      on_evict=self._on_page_evict)
        # serializes page-in / backfill store reads across query threads so
        # concurrent misses for the same partition don't duplicate work
        self._odp_lock = threading.Lock()
        # partitions pinned by an in-flight scan on THIS thread: strong
        # references so mid-query LRU eviction cannot drop them from results
        self._pinned = threading.local()
        self.stats.partitions_paged = 0
        self.stats.chunks_paged = 0

    def _on_page_evict(self) -> None:
        # called after the page-cache lock is released; concurrent evictions
        # from multiple query threads must not lose an increment (a lost
        # bump would leave a grid prep stamped "current" despite an
        # eviction it never observed)
        self.bump_removal_epoch()

    # ------------------------------------------------------------ resolution

    def _partition_for_scan(self, part_id: int) -> Optional[TimeSeriesPartition]:
        pinned = getattr(self._pinned, "parts", None)
        if pinned is not None:
            part = pinned.get(part_id)
            if part is not None:
                return part
        part = self.partitions.get(part_id)
        if part is None:
            part = self.paged.get(part_id)
        return part

    def grid_partition(self, part_id: int) -> Optional[TimeSeriesPartition]:
        """PAGED partitions serve the device grid too: once a dashboard
        pages history in, its chunks register as grid blocks and repeat
        hits serve at device speed (reference:
        DemandPagedChunkStore.scala:34 pages into block memory).  Paged
        partitions hold their FULL persisted history, so the grid's
        disk-floor proof passes naturally; page-cache eviction bumps the
        shard's removal epoch, invalidating grid plans that referenced
        the evicted partition."""
        part = self.partitions.get(part_id)
        if part is None:
            part = self.paged.get(part_id)
        return part

    def _resolve_partitions(self, part_ids: Sequence[int], start_time: int,
                            end_time: int) -> dict[int, TimeSeriesPartition]:
        """Resolve every id, paging absent partitions (full history) and
        backfilling older on-disk chunks of recovery-tail residents.  The
        scanned-bytes cap is enforced BEFORE any vector leaves the store
        (reference: capDataScannedPerShardCheck runs before paging)."""
        resident: dict[int, TimeSeriesPartition] = {}
        missing: list[int] = []
        for pid in part_ids:
            pid = int(pid)
            part = self.partitions.get(pid)
            if part is not None:
                resident[pid] = part
                continue
            part = self.paged.get(pid)
            if part is None:
                missing.append(pid)
            else:
                resident[pid] = part
        self._cap_data_scanned(resident.values(), missing, start_time,
                               end_time)
        for pid, part in list(resident.items()):
            if pid in self.partitions:
                # live partition: may hold only its post-recovery tail
                resident[pid] = self._with_backfill(part)
        if missing:
            self._page_in(missing, resident)
        return resident

    def _with_backfill(self, part: TimeSeriesPartition) -> TimeSeriesPartition:
        """A live partition re-materialized during recovery holds only rows
        replayed after the checkpoint; its older chunks stayed on disk
        (reference: OnDemandPagingShard computes missing chunk time-ranges
        per partition).  Newer-than-resident chunks cannot exist for a live
        partition — it is the single writer of its own tail.

        The live partition is NEVER mutated from the query thread: the
        ingest thread is its single writer.  Instead the older chunks are
        cached in the paged LRU and the scan gets a read-only snapshot
        object whose chunk list is a fresh ``older + live`` copy."""
        earliest = part.earliest_timestamp
        if earliest < 0:
            earliest = _MAX_TIME
        try:
            idx_start = self.index.start_time(part.part_id)
        except KeyError:
            return part
        if idx_start >= earliest:
            return part  # nothing on disk predates memory
        key = ("bf", part.part_id)
        older = self.paged.get(key)
        if older is None:
            with self._odp_lock:
                older = self.paged.get(key)
                if older is None:
                    have = {c.info.chunk_id for c in list(part.chunks)}
                    older = []
                    for _pk, chunksets in self.store.read_raw_partitions(
                            self.dataset, self.shard_num, [part.partkey],
                            idx_start, earliest - 1):
                        older.extend(cs for cs in chunksets
                                     if cs.info.chunk_id not in have)
                    older.sort(key=lambda c: c.info.chunk_id)
                    # cache only while this exact partition object is still
                    # live: a concurrent eviction + re-ingest reuses the pid
                    # and the old list would hide the chunks flushed at
                    # eviction time
                    if self.partitions.get(part.part_id) is part:
                        self.paged.put(key, older,
                                       sum(c.nbytes for c in older))
                    self.stats.chunks_paged += len(older)
        if not older:
            return part
        snap = TimeSeriesPartition.__new__(TimeSeriesPartition)
        for slot in TimeSeriesPartition.__slots__:
            setattr(snap, slot, getattr(part, slot))
        snap.chunks = older + part.chunks   # fresh list; live one untouched
        snap._unflushed = []
        return snap

    def _page_in(self, part_ids: list[int],
                 resident: dict[int, TimeSeriesPartition]) -> None:
        """Materialize fully-absent partitions from disk with their whole
        persisted history, so the cached object serves any time range."""
        with self._odp_lock:
            by_pk = {}
            for pid in part_ids:
                # another query thread may have paged it in while this one
                # waited on the lock
                part = self.paged.get(pid)
                if part is not None:
                    resident[pid] = part
                    continue
                try:
                    by_pk[self.index.partkey(pid)] = pid
                except KeyError:
                    continue  # purged from index since lookup: skip gracefully
            if not by_pk:
                return
            for pk, chunksets in self.store.read_raw_partitions(
                    self.dataset, self.shard_num, list(by_pk), 0, _MAX_TIME):
                pid = by_pk[pk]
                schema = self._schema_for_chunks(chunksets)
                # the index parsed this partkey at recover/create time —
                # reuse its tags dict instead of re-parsing per page-in
                try:
                    tags = self.index.tags(pid)
                except KeyError:
                    tags = parse_partkey(pk)
                part = TimeSeriesPartition(pid, schema, pk, tags,
                                           group=pid % self.num_groups)
                part.chunks = sorted(chunksets, key=lambda c: c.info.chunk_id)
                # paged chunks are already persisted: nothing to flush
                part._unflushed = []
                nbytes = 0
                for cs in part.chunks:
                    nbytes += cs.nbytes
                self.paged.put(pid, part, nbytes)
                resident[pid] = part
                self.stats.partitions_paged += 1
                self.stats.chunks_paged += len(chunksets)

    def _schema_for_chunks(self, chunksets):
        """The persisted schema hash identifies the exact schema; fall back
        to column-count matching for chunks written before hashes were
        stored."""
        h = chunksets[0].schema_hash
        if h:
            try:
                return self.schemas.by_hash(h)
            except KeyError:
                pass
        ncols = len(chunksets[0].vectors)
        candidates = [s for s in self.schemas.all
                      if len(s.data.columns) == ncols]
        for part in self.partitions.values():
            if part.schema in candidates or not candidates:
                return part.schema
        if candidates:
            return candidates[0]
        return self.schemas.all[0]

    # ------------------------------------------------------------ query path

    def scan_batch(self, part_ids: Sequence[int], start_time: int,
                   end_time: int, column_id: Optional[int] = None):
        parts = self._resolve_partitions(part_ids, start_time, end_time)
        # pin resolved partitions for the duration of the scan: later
        # page-ins must not LRU-evict earlier ones out of this query
        self._pinned.parts = parts
        try:
            self._predecode_chunks(parts.values(), start_time, end_time)
            return super().scan_batch(part_ids, start_time, end_time,
                                      column_id)
        finally:
            self._pinned.parts = None

    @staticmethod
    def _predecode_chunks(parts, start_time: int, end_time: int) -> None:
        """Batch-decode every undecoded chunk the scan will touch with
        ONE native call, filling each partition's decoded-chunk cache so
        read_range becomes pure concatenation (reference:
        DemandPagedChunkStore.scala:34 pages straight into block memory;
        VERDICT r4 missing #4 — the cold ODP path paid a per-chunk
        Python decode per partition)."""
        from filodb_tpu.core.chunk import decode_partitions_batch
        groups, owners = [], []
        schema = None
        for part in parts:
            if schema is None:
                schema = part.schema
            elif part.schema.schema_hash != schema.schema_hash:
                return                     # mixed schemas: per-chunk path
            decoded = part._decoded
            for cs in part.chunks:
                if cs.info.end_time < start_time \
                        or cs.info.start_time > end_time \
                        or cs.info.chunk_id in decoded:
                    continue
                groups.append([cs])
                owners.append((part, cs.info.chunk_id))
        if not groups or schema is None:
            return
        for (part, cid), decoded in zip(
                owners, decode_partitions_batch(schema, groups)):
            part._decoded[cid] = decoded

    def _cap_data_scanned(self, resident_parts, missing_ids: Sequence[int],
                          start_time: int, end_time: int) -> None:
        """Only chunks overlapping the query range count against the cap —
        a narrow query over a long-retention series must not be rejected
        for history it will never decode.  Absent partitions are costed
        from store metadata before their vectors are read."""
        total = sum(c.nbytes
                    for p in resident_parts for c in p.chunks
                    if c.info.end_time >= start_time
                    and c.info.start_time <= end_time)
        cap = self.config.max_data_per_shard_query
        if missing_ids and total <= cap:
            pks = []
            for pid in missing_ids:
                try:
                    pks.append(self.index.partkey(pid))
                except KeyError:
                    continue
            if pks:
                total += self.store.scan_bytes(self.dataset, self.shard_num,
                                               pks, start_time, end_time)
        if total > cap:
            raise QueryLimitExceeded(
                f"query would scan {total} bytes on shard {self.shard_num}, "
                f"cap is {cap} (max-data-per-shard-query)")

    def lookup_partitions(self, filters: Sequence[ColumnFilter],
                          start_time: int, end_time: int,
                          limit: Optional[int] = None) -> PartLookupResult:
        """Unlike the in-memory-only base (which reports non-resident ids as
        ``missing_partkeys``), every indexed id is servable here — absent
        partitions page in at scan time."""
        ids = self.index.part_ids_from_filters(filters, start_time, end_time,
                                               limit)
        first_schema = None
        out: list[int] = []
        for i in ids:
            pid = int(i)
            part = self.partitions.get(pid) or self.paged.get(pid)
            if part is not None:
                h = part.schema.schema_hash
            else:
                # absent id: schema hash tracked at create/recover time
                h = self.part_schema_hash.get(pid)
            if h is not None:
                if first_schema is None:
                    first_schema = h
                if h != first_schema:
                    continue  # one schema per lookup, like the base class
            out.append(pid)
        return PartLookupResult(self.shard_num,
                                np.asarray(out, dtype=np.int32), [],
                                first_schema)

    # -------------------------------------------------------------- eviction

    def evict_partitions(self, n: int) -> int:
        """Unlike the base (in-memory-only) shard, keep index + part-set
        entries so queries can page evicted series back from disk
        (reference: Lucene entries survive eviction; evicted partkeys
        tracked in a bloom filter, TimeSeriesShard.scala:1308-1401)."""
        # stopped-longest-ago first; ghost ids (already evicted, still
        # indexed) must not consume the quota
        stopped = [pid for pid in
                   self.index.part_ids_ordered_by_end_time(
                       n + max(len(self.index_only_ids()), 0))
                   if pid in self.partitions]
        victims = stopped[:n]
        if len(victims) < n:
            # not enough stopped series: fall back to least-recently-written
            # active partitions (they are safely pageable once flushed)
            seen = set(victims)
            active = sorted((p.latest_timestamp, pid)
                            for pid, p in self.partitions.items()
                            if pid not in seen)
            victims += [pid for _, pid in active[:n - len(victims)]]
        evicted = 0
        itime = int(time.time() * 1000)
        for pid in victims:
            part = self.partitions.get(pid)
            if part is None:
                continue
            # persist anything not yet flushed — eviction must not lose data,
            # must stay visible to ingestion-time scans (batch downsampler),
            # and must still feed the streaming downsampler
            pending = part.make_flush_chunks()
            if pending:
                self.store.write_chunks(self.dataset, self.shard_num, pending,
                                        ingestion_time=itime)
                self.store.write_part_keys(
                    self.dataset, self.shard_num,
                    [PartKeyRecord(part.partkey, self.index.start_time(pid),
                                   self.index.end_time(pid), self.shard_num,
                                   part.schema.schema_hash)])
                if self.downsample_publisher is not None:
                    self._downsampler_for(
                        part.schema.schema_hash).downsample_chunksets(
                        [(part.tags, cs) for cs in pending])
            # under _odp_lock so an in-flight backfill compute for this pid
            # finishes (and its live-partition identity check then fails)
            # before the stale entries are dropped
            with self._odp_lock:
                del self.partitions[pid]
                self.bump_removal_epoch()    # invalidates grid prep caches
                self.paged.pop(pid)          # cached copy lacks the tail
                self.paged.pop(("bf", pid))  # list is live-part relative
            self.evicted_keys.add(part.partkey)
            self.stats.partitions_evicted += 1
            evicted += 1
        return evicted

    def index_only_ids(self) -> list[int]:
        """Ids present in the index but not resident in memory."""
        return [pid for pid in self.part_set.values()
                if pid not in self.partitions]
