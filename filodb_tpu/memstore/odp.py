"""On-demand paging: serve queries for series whose chunks live only on disk.

Capability match for the reference's OnDemandPagingShard +
DemandPagedChunkStore (reference: core/src/main/scala/filodb.core/
memstore/OnDemandPagingShard.scala, DemandPagedChunkStore.scala:34): on
query, partitions found in the tag index but absent from memory (evicted,
or index-bootstrapped after restart) have their raw chunks read back from
the ColumnStore and re-materialized.  Paged partitions are read-only and
live in a bytes-bounded LRU cache — the stand-in for time-bucketed block
memory with reclaim-on-demand.  A paged partition always holds its FULL
persisted history (cache granularity is the partition), so repeated
queries at different ranges see consistent data.

Also enforces the per-query scanned-data cap over chunks overlapping the
query range (``StoreConfig.max_data_per_shard_query``; reference
capDataScannedPerShardCheck).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import struct
import threading
import time
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from filodb_tpu import integrity
from filodb_tpu.core.chunk import (ChunkBatch, ChunkSet, ChunkSetInfo,
                                   counts_pad, fill_batch_pads, pad_rows)
from filodb_tpu.integrity import IntegrityInvariantError
from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.record import parse_partkey
from filodb_tpu.core.schemas import ColumnType
from filodb_tpu.memstore.partition import TimeSeriesPartition
from filodb_tpu.memstore.shard import (PartLookupResult, TimeSeriesShard,
                                       _round_up)
from filodb_tpu.store.columnstore import PartKeyRecord, ScanBytesExceeded

_MAX_TIME = 2**62

_LOG = logging.getLogger("filodb.odp")
_EMPTY_I64 = np.empty(0, dtype=np.int64)
_U16 = struct.Struct("<H")

_NUMERIC = (ColumnType.TIMESTAMP, ColumnType.LONG, ColumnType.INT,
            ColumnType.DOUBLE)


class QueryLimitExceeded(Exception):
    """A query would scan more bytes than max_data_per_shard_query allows."""


def _active_ctx():
    """The ExecContext of the scan on THIS thread (None off the query
    path, e.g. the deferred publish thread).  Lazy import: exec.py
    imports the memstore package at module load."""
    from filodb_tpu.query.exec import active_exec_ctx
    return active_exec_ctx()


_ODP_METRICS = None


def _odp_m() -> dict:
    """The filodb_odp_* metric objects, resolved ONCE — page-ins must
    not serialize on the registry lock for pure lookups."""
    global _ODP_METRICS
    if _ODP_METRICS is None:
        from filodb_tpu.utils.observability import odp_metrics
        _ODP_METRICS = odp_metrics()
    return _ODP_METRICS


@contextlib.contextmanager
def _pagein_timed(shard, kind: str):
    """Span + filodb_odp_* latency + per-query decode-stage attribution
    around a page-in (reference: Kamon spans around ODP,
    OnDemandPagingShard.scala)."""
    from filodb_tpu.utils.observability import TRACER
    t0 = time.perf_counter()
    try:
        with TRACER.span("odp.pagein", dataset=shard.dataset,
                         shard=shard.shard_num, kind=kind):
            yield
    finally:
        dt = time.perf_counter() - t0
        _odp_m()["pagein_seconds"].observe(dt, dataset=shard.dataset)
        ctx = _active_ctx()
        if ctx is not None:
            ctx.note_timing("decode", dt)


class _LazyVectors:
    """Sequence of encoded vector spans, unpacked from the framed row
    blob on first access.  Bulk-paged partitions rarely touch the
    encoded bytes — queries serve from the pre-filled decoded cache —
    so the per-row unpack is deferred until something (grid staging,
    debug CLI) actually asks."""

    __slots__ = ("_blob", "_vecs")

    def __init__(self, blob: bytes):
        self._blob = blob
        self._vecs = None

    def _force(self) -> list:
        if self._vecs is None:
            from filodb_tpu.store.persistence import unpack_vectors
            self._vecs = unpack_vectors(self._blob)
        return self._vecs

    def __getitem__(self, i):
        return self._force()[i]

    def __len__(self) -> int:
        return len(self._force())

    def __iter__(self):
        return iter(self._force())


@dataclasses.dataclass
class PagedChunkSet(ChunkSet):
    """ChunkSet over a raw framed ColumnStore row blob: ``vectors``
    unpack lazily and ``nbytes`` is precomputed — the bulk page-in
    builds thousands per query and only the decoded cache is hot."""

    raw_nbytes: int = 0

    @property
    def nbytes(self) -> int:
        return self.raw_nbytes


class _PagedPartitions:
    """Bytes-bounded LRU of read-only re-materialized partitions (int keys)
    and backfill chunk lists for live partitions (``("bf", pid)`` keys).

    All methods take an internal lock: ODP shards are queried concurrently
    from HTTP handler threads, so the OrderedDict reorder + byte accounting
    must not interleave."""

    def __init__(self, max_bytes: int, on_evict=None):
        self.max_bytes = max_bytes
        self._entries: OrderedDict = OrderedDict()   # key -> (value, nbytes)
        self._bytes = 0
        self._lock = threading.Lock()
        # invalidation generations: pop() stamps the key with a bumped
        # gen under the lock, and a deferred put_many carrying gen_guard
        # drops exactly the items whose key was popped SINCE the guard
        # was captured — so an evict's pop and a late publish's insert
        # are safe in EITHER order (pop-then-insert would otherwise
        # resurrect a stale partition missing chunks flushed at
        # eviction), while unrelated evictions don't cancel a
        # cold-dashboard publish wholesale.  _pop_floor bounds the stamp
        # map: below it, guarded puts drop everything (rare overflow)
        self.gen = 0
        self._pop_gen: dict = {}
        self._pop_floor = 0
        # called AFTER put releases the lock when LRU pressure dropped an
        # entry (deadlock-safe; implementations must not assume mutual
        # exclusion with concurrent put/get) — the ODP shard bumps its
        # removal epoch so grid plan memos referencing the evicted
        # partition revalidate
        self._on_evict = on_evict

    def get(self, key):
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            self._entries.move_to_end(key)
            return ent[0]

    def put(self, key, value, nbytes: int) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            evicted = False
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_ev, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                evicted = True
        if evicted and self._on_evict is not None:
            self._on_evict()

    def snapshot(self) -> dict:
        """One-lock read view {key: value} — the bulk scan classifies
        thousands of ids without a lock round-trip (and LRU reorder)
        per id; recency is restored afterwards via :meth:`touch_many`."""
        with self._lock:
            return {k: v[0] for k, v in self._entries.items()}

    def touch_many(self, keys: Sequence) -> None:
        """Refresh LRU recency for keys served by a bulk scan (one lock
        for the whole batch)."""
        with self._lock:
            move = self._entries.move_to_end
            for k in keys:
                if k in self._entries:
                    move(k)

    def put_many(self, items: Sequence[tuple],
                 gen_guard: Optional[int] = None) -> None:
        """Batch put of (key, value, nbytes): ONE lock acquisition for a
        bulk page-in (thousands of partitions per cold dashboard).  With
        ``gen_guard``, items whose key was pop()ed since the guard was
        captured are dropped (deferred publishes must not resurrect
        explicitly-invalidated partitions; the rest of the batch still
        lands)."""
        with self._lock:
            if gen_guard is not None:
                if gen_guard < self._pop_floor:
                    return          # stamp map overflowed: conservative
                pg = self._pop_gen
                if pg:
                    items = [it for it in items
                             if pg.get(it[0], 0) <= gen_guard]
            for key, value, nbytes in items:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old[1]
                self._entries[key] = (value, nbytes)
                self._bytes += nbytes
            evicted = False
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_ev, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                evicted = True
        if evicted and self._on_evict is not None:
            self._on_evict()

    def pop(self, key) -> None:
        with self._lock:
            self.gen += 1
            self._pop_gen[key] = self.gen   # cancels in-flight publish
            if len(self._pop_gen) > 65536:  # bound the stamp map
                self._pop_floor = self.gen
                self._pop_gen.clear()
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            if self._bytes < 0:   # O(1) reclaim-bookkeeping tripwire
                raise IntegrityInvariantError(
                    f"paged LRU byte accounting went negative "
                    f"({self._bytes}) popping {key!r}")

    def current_gen(self) -> int:
        """The invalidation generation read UNDER the lock — guard
        capture for a deferred publish must not race a concurrent
        pop()'s bump (ADVICE r5 finding 2: an unlocked read relied on
        every pop() caller holding the shard's _odp_lock)."""
        with self._lock:
            return self.gen

    def check_invariants(self) -> None:
        """Hard reclaim-bookkeeping check: tracked bytes must equal the
        sum over live entries.  Raises IntegrityInvariantError on drift
        — callers fail the shard rather than serve stale buffers (the
        reference's reclaim meta-size check kills the process,
        TimeSeriesShard.scala:279-301)."""
        with self._lock:
            actual = sum(nb for _v, nb in self._entries.values())
            if actual != self._bytes or self._bytes < 0:
                raise IntegrityInvariantError(
                    f"paged LRU byte accounting drift: tracked="
                    f"{self._bytes} actual={actual} "
                    f"entries={len(self._entries)}")

    def __len__(self) -> int:
        """Number of cached whole partitions (backfill entries excluded)."""
        with self._lock:
            return sum(1 for k in self._entries if isinstance(k, int))


class OnDemandPagingShard(TimeSeriesShard):
    """TimeSeriesShard that pages missing partitions from the ColumnStore."""

    def __init__(self, *args, page_cache_bytes: Optional[int] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        if page_cache_bytes is None:
            page_cache_bytes = self.config.page_cache_bytes
        self.paged = _PagedPartitions(page_cache_bytes,
                                      on_evict=self._on_page_evict)
        # devicewatch ledger: the page cache is a budgeted resident
        # arena like the HBM grids — register it as a sampled pool so
        # /admin/device and filodb_device_hbm_bytes show who holds it
        from filodb_tpu.utils.devicewatch import LEDGER
        self._ledger_owner = f"odp:{self.dataset}/{self.shard_num}"
        paged = self.paged
        LEDGER.register_pool(self._ledger_owner,
                             lambda: paged._bytes,
                             lambda: paged.max_bytes)
        # serializes page-in / backfill store reads across query threads so
        # concurrent misses for the same partition don't duplicate work
        # (outermost in the paging hierarchy, enforced by filolint):
        # lock-order: _odp_lock < _PagedPartitions._lock
        self._odp_lock = threading.Lock()
        # partitions pinned by an in-flight scan on THIS thread: strong
        # references so mid-query LRU eviction cannot drop them from results
        self._pinned = threading.local()
        # in-flight deferred page-cache publishes (fused cold scans hand
        # the query its batch first and materialize skeletons for the
        # cache on this side thread — reference:
        # DemandPagedChunkStore.scala:34 pages into block memory via
        # futures too); queries that MISS the cache join these first so
        # a publish-in-progress never causes a redundant re-page.  Each
        # entry is (thread, frozenset of pids the publish will land) so
        # per-pid misses join ONLY publishes that could contain them.
        self._mat_tasks: list[tuple[threading.Thread, frozenset]] = []
        self.stats.partitions_paged = 0
        self.stats.chunks_paged = 0
        self.stats.page_publish_errors = 0
        # bulk page-decode calls that hit a corrupt-input sentinel and
        # fell back to the per-chunk path (which diagnoses + quarantines)
        self.stats.page_decode_corrupt = 0

    def close(self) -> None:
        """The page-cache pool registration is a set_fn gauge holding
        this shard's paged-LRU alive — deregister it on teardown (the
        leak the resource-lifecycle lint exists to catch)."""
        from filodb_tpu.utils.devicewatch import LEDGER
        LEDGER.deregister_pool(self._ledger_owner)
        super().close()

    def _join_materialize(self, part_id: Optional[int] = None) -> None:
        # peek-join-remove (NOT pop-then-join): a task must stay visible
        # to concurrent threads until its publish has actually landed,
        # or a third thread could classify a miss mid-publish and
        # duplicate the whole store read.  With ``part_id``, only joins
        # publishes whose pid set could contain it — a cache-miss
        # reader must not block behind an unrelated cold dashboard's
        # thousand-partition page-in (ADVICE r5 #4); the argless form
        # (bulk classification under _odp_lock) still joins everything.
        while True:
            tasks = [e for e in self._mat_tasks
                     if part_id is None or part_id in e[1]]
            if not tasks:
                return
            entry = tasks[-1]
            entry[0].join()
            try:
                self._mat_tasks.remove(entry)
            except ValueError:
                pass       # another joiner removed it after its join

    def _paged_or_join(self, part_id: int) -> Optional[TimeSeriesPartition]:
        """Page-cache read that joins an in-flight deferred publish on a
        miss (shared by every per-pid resolution path).  Joins ONLY
        publishes tracking this pid, so an unrelated publish-in-progress
        never serializes this reader behind it."""
        part = self.paged.get(part_id)
        if part is None and self._mat_tasks:
            self._join_materialize(part_id)
            part = self.paged.get(part_id)
        return part

    def _note_paged(self, nparts: int, nchunks: int) -> None:
        """Page-in accounting in ONE place: shard stats, the
        filodb_odp_* counters, and the active query's pages-in/chunks
        resource counters (absent on the deferred publish thread)."""
        m = _odp_m()
        if nparts:
            self.stats.partitions_paged += nparts
            m["partitions"].inc(nparts, dataset=self.dataset)
        if nchunks:
            self.stats.chunks_paged += nchunks
            m["chunks"].inc(nchunks, dataset=self.dataset)
        ctx = _active_ctx()
        if ctx is not None:
            ctx.note_counts(chunks=nchunks, pages=nparts)
        if nparts or nchunks:
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("odp.pagein", dataset=self.dataset,
                          shard=self.shard_num, partitions=nparts,
                          chunks=nchunks)

    def _prefetch_cold_for(self, part_ids: Sequence[int], start_time: int,
                           end_time: int) -> None:
        """Stage any cold-bucket objects the coming page-in will need,
        BEFORE _odp_lock is taken: bucket I/O (and bucket stalls) must
        never run under the lock every query thread serializes on.  A
        stalled bucket raises BucketTimeout here — aborting this query
        lock-free while others proceed — and the locked read below
        consumes the staged bytes without touching the bucket.  The
        candidate set is computed lock-free and can race concurrent
        page-ins; a raced-in partition just means a staged blob goes
        unconsumed (bounded by the store's staging cap)."""
        prefetch = getattr(self.store, "prefetch_cold", None)
        if prefetch is None:
            return
        pks = []
        for pid in part_ids:
            if self.paged.get(pid) is not None:
                continue
            try:
                pks.append(self.index.partkey(pid))
            except KeyError:
                continue
        if not pks:
            return
        # mirror the bulk read's full-scan heuristic so the staged set
        # covers what the locked read will actually ask for
        full = len(pks) > 256 and 2 * len(pks) >= len(self.part_set)
        prefetch(self.dataset, self.shard_num, None if full else pks,
                 start_time, end_time)

    def _on_page_evict(self) -> None:
        # called after the page-cache lock is released; concurrent evictions
        # from multiple query threads must not lose an increment (a lost
        # bump would leave a grid prep stamped "current" despite an
        # eviction it never observed)
        self.bump_removal_epoch()
        from filodb_tpu.utils.devicewatch import LEDGER
        LEDGER.note_eviction(self._ledger_owner, "budget_overflow")

    # ------------------------------------------------------------ resolution

    def _partition_for_scan(self, part_id: int) -> Optional[TimeSeriesPartition]:
        pinned = getattr(self._pinned, "parts", None)
        if pinned is not None:
            part = pinned.get(part_id)
            if part is not None:
                return part
        part = self.partitions.get(part_id)
        if part is None:
            part = self._paged_or_join(part_id)
        return part

    def grid_partition(self, part_id: int) -> Optional[TimeSeriesPartition]:
        """PAGED partitions serve the device grid too: once a dashboard
        pages history in, its chunks register as grid blocks and repeat
        hits serve at device speed (reference:
        DemandPagedChunkStore.scala:34 pages into block memory).  Paged
        partitions hold their FULL persisted history, so the grid's
        disk-floor proof passes naturally; page-cache eviction bumps the
        shard's removal epoch, invalidating grid plans that referenced
        the evicted partition."""
        part = self.partitions.get(part_id)
        if part is None:
            part = self._paged_or_join(part_id)
        return part

    def _resolve_partitions(self, part_ids: Sequence[int], start_time: int,
                            end_time: int) -> dict[int, TimeSeriesPartition]:
        """Resolve every id, paging absent partitions (full history) and
        backfilling older on-disk chunks of recovery-tail residents.  The
        scanned-bytes cap is enforced BEFORE any vector leaves the store
        (reference: capDataScannedPerShardCheck runs before paging)."""
        resident: dict[int, TimeSeriesPartition] = {}
        missing: list[int] = []
        for pid in part_ids:
            pid = int(pid)
            part = self.partitions.get(pid)
            if part is not None:
                resident[pid] = part
                continue
            part = self.paged.get(pid)
            if part is None:
                missing.append(pid)
            else:
                resident[pid] = part
        self._cap_data_scanned(resident.values(), missing, start_time,
                               end_time)
        for pid, part in list(resident.items()):
            if pid in self.partitions:
                # live partition: may hold only its post-recovery tail
                resident[pid] = self._with_backfill(part)
        if missing:
            self._page_in(missing, resident)
        return resident

    def _with_backfill(self, part: TimeSeriesPartition) -> TimeSeriesPartition:
        """A live partition re-materialized during recovery holds only rows
        replayed after the checkpoint; its older chunks stayed on disk
        (reference: OnDemandPagingShard computes missing chunk time-ranges
        per partition).  Newer-than-resident chunks cannot exist for a live
        partition — it is the single writer of its own tail.

        The live partition is NEVER mutated from the query thread: the
        ingest thread is its single writer.  Instead the older chunks are
        cached in the paged LRU and the scan gets a read-only snapshot
        object whose chunk list is a fresh ``older + live`` copy."""
        earliest = part.earliest_timestamp
        if earliest < 0:
            earliest = _MAX_TIME
        try:
            idx_start = self.index.start_time(part.part_id)
        except KeyError:
            return part
        if idx_start >= earliest:
            return part  # nothing on disk predates memory
        key = ("bf", part.part_id)
        older = self.paged.get(key)
        if older is None:
            # stage cold objects lock-free first (wasted only if another
            # thread backfills the same partition while we wait)
            prefetch = getattr(self.store, "prefetch_cold", None)
            if prefetch is not None:
                prefetch(self.dataset, self.shard_num, [part.partkey],
                         idx_start, earliest - 1)
            with self._odp_lock:
                older = self.paged.get(key)
                if older is None:
                    have = {c.info.chunk_id for c in list(part.chunks)}
                    older = []
                    for _pk, chunksets in self.store.read_raw_partitions(
                            self.dataset, self.shard_num, [part.partkey],
                            idx_start, earliest - 1):
                        older.extend(cs for cs in chunksets
                                     if cs.info.chunk_id not in have)
                    older.sort(key=lambda c: c.info.chunk_id)
                    # cache only while this exact partition object is still
                    # live: a concurrent eviction + re-ingest reuses the pid
                    # and the old list would hide the chunks flushed at
                    # eviction time
                    if self.partitions.get(part.part_id) is part:
                        self.paged.put(key, older,
                                       sum(c.nbytes for c in older))
                    self._note_paged(0, len(older))
        if not older:
            return part
        snap = TimeSeriesPartition.__new__(TimeSeriesPartition)
        for slot in TimeSeriesPartition.__slots__:
            setattr(snap, slot, getattr(part, slot))
        snap.chunks = older + part.chunks   # fresh list; live one untouched
        snap._unflushed = []
        return snap

    def _page_in_bulk(self, part_ids: Sequence[int],
                      byte_cap: Optional[int] = None, fuse=None):
        """Vectorized page-in: ONE sqlite pass for the raw framed rows
        (a full-shard range scan when most of the shard is wanted), one
        native decode call per column for the whole set, partitions
        materialized with their decoded caches pre-filled (reference:
        DemandPagedChunkStore.scala:34 pages raw chunks straight into
        block memory — no per-chunk object dance).

        Returns None when the set needs the per-partition path (native
        off, store without raw rows, mixed/unknown schemas, non-numeric
        columns), else ``(built, tags, batch)``: pid->part, plus a
        ready query batch when ``fuse=(ids, start, end, column_id)``
        applied — the triggering query's padded [S, R] matrices are
        then written DIRECTLY by the native decoder (out_starts), so
        serving the cold query costs no second assembly pass.  The
        returned batch ALIASES the partitions' cached decoded planes —
        callers must treat ChunkBatch arrays as read-only (they already
        must: generic read_range hands out decoded-cache views too).
        ``byte_cap`` streams through to the store read; crossing it
        raises ScanBytesExceeded (caller decides)."""
        from filodb_tpu import native
        nb = native.batch_decoder()
        if nb is None:
            return None
        self._prefetch_cold_for(part_ids, 0, _MAX_TIME)
        with self._odp_lock:
            # a publish deferred by the PREVIOUS lock holder must land
            # before this query classifies hits/misses, or it would
            # re-read the whole set from the store (publishes don't take
            # _odp_lock, so joining under it cannot deadlock)
            self._join_materialize()  # filolint: disable=blocking-under-lock — deliberate: deferred publishes never take _odp_lock, so joining under it cannot deadlock, and classification must not race a landing publish (ADVICE r5 #4)
            built: dict[int, TimeSeriesPartition] = {}
            by_pk: dict[bytes, int] = {}
            for pid in part_ids:
                part = self.paged.get(pid)   # raced another query thread
                if part is not None:
                    built[pid] = part
                    continue
                try:
                    by_pk[self.index.partkey(pid)] = pid
                except KeyError:
                    continue  # purged from index since lookup
            if not by_pk:
                return built, None, None
            # pre-read eligibility: create/recover-time schema hashes
            # decide most ineligible shards (hist/string columns, mixed
            # schemas) WITHOUT paying the sqlite read that the generic
            # fallback would then repeat
            hs = {self.part_schema_hash.get(pid) for pid in by_pk.values()}
            if None not in hs and 0 not in hs:
                if len(hs) > 1:
                    return None          # mixed schemas: generic path
                try:
                    sch = self.schemas.by_hash(next(iter(hs)))
                except KeyError:
                    return None
                if any(c.ctype not in _NUMERIC
                       for c in sch.data.columns[1:]):
                    return None          # hist/string: generic path
            # most-of-the-shard page-ins walk the primary key range
            # instead of binding thousands of point lookups
            full = len(by_pk) > 256 \
                and 2 * len(by_pk) >= len(self.part_set)
            # defer_verify: the native decoder CRC-checks every selected
            # row span on the join it builds anyway (crcs= below), so
            # the store skips its own checksum pass — rows the full
            # scan over-returns are never verified OR decoded
            rows = self.store.read_raw_rows(self.dataset, self.shard_num,
                                            None if full else list(by_pk),
                                            0, _MAX_TIME,
                                            byte_cap=byte_cap,
                                            defer_verify=True)
            if rows is None:
                return None          # store has no bulk read
            # group by partkey runs, skipping rows the full scan
            # over-returned; fold schema/time bounds into the same pass
            sel: list[tuple] = []
            groups: list[tuple] = []   # (pid, si, sj, rows_total)
            gmin, gmax = _MAX_TIME, -_MAX_TIME
            h0 = None        # from the first SELECTED row: a full scan
            #                  over-returns rows of other schemas
            uniform = True
            i, n = 0, len(rows)
            while i < n:
                pk = rows[i][0]
                j = i
                while j < n and rows[j][0] == pk:
                    j += 1
                pid = by_pk.get(pk)
                if pid is not None:
                    si = len(sel)
                    c = 0
                    for k in range(i, j):
                        r = rows[k]
                        c += r[2]
                        if r[3] < gmin:
                            gmin = r[3]
                        if r[4] > gmax:
                            gmax = r[4]
                        if r[5] != h0:
                            if h0 is None:
                                h0 = r[5]
                            else:
                                uniform = False
                        sel.append(r)
                    groups.append((pid, si, len(sel), c))
                i = j
            del rows
            if not groups:
                return built, None, None
            if not h0 or not uniform:
                return None          # mixed/legacy schemas: generic path
            try:
                schema = self.schemas.by_hash(h0)
            except KeyError:
                return None
            data_cols = schema.data.columns[1:]
            if any(c.ctype not in _NUMERIC for c in data_cols):
                return None          # hist/string columns: generic path
            row_counts = [r[2] for r in sel]
            blobs = [r[6] for r in sel]
            # stored checksums ride along (deferred store verification:
            # the decode calls below verify these on their own join);
            # honor the global verify switch here too — the store was
            # told to defer, so this is where OFF must actually mean off
            import operator
            crcs = list(map(operator.itemgetter(7), sel)) \
                if len(sel[0]) > 7 and integrity.verify_enabled() else None
            dec_row_bytes = 8 * len(schema.data.columns)
            # ---- fused: decode straight into the query's padded batch
            fcid = None
            if fuse is not None and not built \
                    and fuse[1] <= gmin and gmax <= fuse[2]:
                fcid = schema.data.value_column_id if fuse[3] is None \
                    else fuse[3]
                if not (1 <= fcid < len(schema.data.columns)) \
                        or schema.data.columns[fcid].ctype \
                        != ColumnType.DOUBLE:
                    fcid = None
            if fcid is not None:
                present = {g[0] for g in groups}
                order = [pid for pid in fuse[0] if pid in present]
                if len(order) != len(present):
                    fcid = None      # duplicate ids: generic semantics
            if fcid is not None:
                idx_of = {pid: x for x, pid in enumerate(order)}
                counts = np.zeros(len(order), dtype=np.int64)
                for pid, _si, _sj, c in groups:
                    counts[idx_of[pid]] = c
                S = len(order)
                R = pad_rows(int(counts.max()),
                             self.config.batch_row_pad)
                S_pad = max(S, _round_up(S,
                                         self.config.batch_series_pad))
                out_starts = np.empty(len(sel), dtype=np.int64)
                for pid, si, sj, _c in groups:
                    run = idx_of[pid] * R
                    for k in range(si, sj):
                        out_starts[k] = run
                        run += row_counts[k]
                ts2d = np.empty((S_pad, R), dtype=np.int64)
                val2d = np.empty((S_pad, R), dtype=np.float64)
                extra = [(jj, c.ctype == ColumnType.DOUBLE)
                         for jj, c in enumerate(data_cols, start=1)
                         if jj != fcid]
                eflats = None
                # crcs on the FIRST call only: one verify per row set
                if nb.page_decode_into(blobs, row_counts,
                                       [(0, False, ts2d),
                                        (fcid, True, val2d)], out_starts,
                                       crcs=crcs):
                    eflats = nb.page_decode(blobs, row_counts, extra) \
                        if extra else []
                if eflats is None:
                    # corrupt-input sentinel (checksum or decode): count
                    # it, then the generic path re-reads store-verified
                    # rows, re-decodes per chunk, diagnoses, quarantines
                    self.stats.page_decode_corrupt += 1
                    return None
                self._count_verified(len(sel), crcs)
                cnts = counts_pad(counts.astype(np.int32), S_pad)
                fill_batch_pads(ts2d, val2d, cnts, S)
                epref = np.concatenate(
                    ([0], np.cumsum(row_counts))).tolist() if extra \
                    else None
                ncols = len(schema.data.columns)

                def views(k, x, run, nr):
                    lo, hi = run, run + nr
                    colviews, e = [], 0
                    for jj in range(1, ncols):
                        if jj == fcid:
                            colviews.append(val2d[x, lo:hi])
                        else:
                            colviews.append(
                                eflats[e][epref[k]:epref[k] + nr])
                            e += 1
                    return ts2d[x, lo:hi], colviews

                # the triggering query needs only tags + the decoded
                # batch; skeleton construction + LRU publish (the other
                # ~40% of the cold budget) runs on a side thread.  Stats
                # count NOW so callers see the page-in they just caused.
                tags_of = self.index.tags
                tags_list: list = [None] * len(order)
                for pid, si, _sj, _c in groups:
                    try:
                        tags = tags_of(pid)
                    except KeyError:
                        tags = parse_partkey(sel[si][0])
                    tags_list[idx_of[pid]] = tags
                self._note_paged(len(groups), len(sel))
                # pop()s since this point cancel the publish (gen_guard);
                # read under the cache lock so a concurrent pop cannot
                # slip between the read and the guard capture
                gen0 = self.paged.current_gen()

                def publish():
                    # lock-free: everything touched (page-cache, index
                    # tag reads) locks internally, so joiners holding
                    # _odp_lock cannot deadlock on this thread
                    try:
                        self._materialize_paged(sel, groups, schema,
                                                dec_row_bytes, idx_of,
                                                views, {},
                                                count_stats=False,
                                                gen_guard=gen0,
                                                tags_by_x=tags_list)
                    except Exception:
                        # the triggering query already succeeded; a
                        # failed publish only loses cache warmth — but
                        # must be visible, not silent
                        self.stats.page_publish_errors += 1
                        _LOG.exception("deferred page-cache publish "
                                       "failed (shard %s)",
                                       self.shard_num)

                t = threading.Thread(target=publish, name="odp-publish",
                                     daemon=True)
                t.start()   # started BEFORE it is joinable via the list
                self._mat_tasks.append(
                    (t, frozenset(g[0] for g in groups)))
                return built, tags_list, ChunkBatch(ts2d, val2d, cnts)
            # ---- flat decode: fills decoded caches only
            cols = [(0, False)] + [
                (j, c.ctype == ColumnType.DOUBLE)
                for j, c in enumerate(data_cols, start=1)]
            flats = nb.page_decode(blobs, row_counts, cols, crcs=crcs)
            if flats is None:
                # corrupt-input sentinel (checksum or decode): count +
                # fall back (the generic store-verified per-chunk path
                # diagnoses and quarantines the culprit)
                self.stats.page_decode_corrupt += 1
                return None
            self._count_verified(len(sel), crcs)
            oo = np.concatenate(([0], np.cumsum(row_counts))).tolist()
            ts_flat, val_flats = flats[0], flats[1:]

            def views(k, _x, _run, _nr):
                lo, hi = oo[k], oo[k + 1]
                return ts_flat[lo:hi], [f[lo:hi] for f in val_flats]

            self._materialize_paged(sel, groups, schema, dec_row_bytes,
                                    None, views, built)
            return built, None, None

    def _materialize_paged(self, sel, groups, schema, dec_row_bytes,
                           idx_of, views, built,
                           count_stats: bool = True,
                           gen_guard: Optional[int] = None,
                           tags_by_x: Optional[list] = None) -> None:
        """Shared construction tail of the bulk page-in (ONE copy for
        the fused and flat branches): read-only partition skeletons,
        lazily-framed PagedChunkSets, decoded caches filled from the
        ``views(k, series_index, run, nr)`` callback, LRU publish and
        stats.  Runs either under ``_odp_lock`` (flat branch) or on the
        deferred publish thread WITHOUT it (every structure it touches
        locks internally); strong refs stay in ``built`` (LRU pressure
        here may evict entries from the cache but never from the
        in-flight query)."""
        tags_of = self.index.tags
        items = []
        for pid, si, sj, _c in groups:
            pk = sel[si][0]
            x = idx_of[pid] if idx_of is not None else 0
            if tags_by_x is not None:
                # the fused branch already resolved these for the query
                # response; reuse them so the cached partition and the
                # response can never diverge (and the publish thread
                # skips a second full index pass)
                tags = tags_by_x[x]
            else:
                try:
                    tags = tags_of(pid)
                except KeyError:
                    tags = parse_partkey(pk)
            # write buffers are lazy, so the plain constructor costs
            # only the attribute sets — no skeleton shortcut needed
            part = TimeSeriesPartition(pid, schema, pk, tags,
                                       group=pid % self.num_groups)
            part.on_corrupt = self.note_corrupt_chunk
            chunks, decoded, nbytes = [], {}, 0
            run = 0
            for k in range(si, sj):
                _pk, cidk, nr, st, et, shh, blob = sel[k][:7]
                (nvec,) = _U16.unpack_from(blob, 0)
                raw_nb = len(blob) - 2 - 4 * nvec
                chunks.append(PagedChunkSet(
                    ChunkSetInfo(cidk, nr, st, et), pk,
                    _LazyVectors(blob), schema_hash=shh,
                    raw_nbytes=raw_nb))
                decoded[cidk] = views(k, x, run, nr)
                run += nr
                # account the DECODED bytes too: the views pin shared
                # batch-wide planes, so the LRU budget must reflect
                # decoded residency, not just the compressed blob size
                nbytes += raw_nb + nr * dec_row_bytes
            part.chunks = chunks
            part._decoded = decoded
            items.append((pid, part, nbytes))
            built[pid] = part
        self.paged.put_many(items, gen_guard=gen_guard)
        if count_stats:
            self._note_paged(len(items), len(sel))

    def _page_in(self, part_ids: list[int],
                 resident: dict[int, TimeSeriesPartition]) -> None:
        """Materialize fully-absent partitions from disk with their whole
        persisted history, so the cached object serves any time range."""
        with _pagein_timed(self, "generic"):
            self._page_in_inner(part_ids, resident)

    def _page_in_inner(self, part_ids: list[int],
                       resident: dict[int, TimeSeriesPartition]) -> None:
        got = self._page_in_bulk(part_ids)
        if got is not None:
            resident.update(got[0])
            return
        # generic path: re-stage lock-free (no-op for keys the bulk
        # attempt already staged — the staging dict persists per thread)
        self._prefetch_cold_for(part_ids, 0, _MAX_TIME)
        with self._odp_lock:
            self._join_materialize()  # filolint: disable=blocking-under-lock — see _page_in_bulk: publishes never take _odp_lock; join-under-lock is the no-duplicate-page-in invariant
            by_pk = {}
            for pid in part_ids:
                # another query thread may have paged it in while this one
                # waited on the lock
                part = self.paged.get(pid)
                if part is not None:
                    resident[pid] = part
                    continue
                try:
                    by_pk[self.index.partkey(pid)] = pid
                except KeyError:
                    continue  # purged from index since lookup: skip gracefully
            if not by_pk:
                return
            for pk, chunksets in self.store.read_raw_partitions(
                    self.dataset, self.shard_num, list(by_pk), 0, _MAX_TIME):
                pid = by_pk[pk]
                schema = self._schema_for_chunks(chunksets)
                # the index parsed this partkey at recover/create time —
                # reuse its tags dict instead of re-parsing per page-in
                try:
                    tags = self.index.tags(pid)
                except KeyError:
                    tags = parse_partkey(pk)
                part = TimeSeriesPartition(pid, schema, pk, tags,
                                           group=pid % self.num_groups)
                part.on_corrupt = self.note_corrupt_chunk
                part.chunks = sorted(chunksets, key=lambda c: c.info.chunk_id)
                # paged chunks are already persisted: nothing to flush
                part._unflushed = []
                nbytes = 0
                for cs in part.chunks:
                    nbytes += cs.nbytes
                self.paged.put(pid, part, nbytes)
                resident[pid] = part
                self._note_paged(1, len(chunksets))

    def _schema_for_chunks(self, chunksets):
        """The persisted schema hash identifies the exact schema; fall back
        to column-count matching for chunks written before hashes were
        stored."""
        h = chunksets[0].schema_hash
        if h:
            try:
                return self.schemas.by_hash(h)
            except KeyError:
                pass
        ncols = len(chunksets[0].vectors)
        candidates = [s for s in self.schemas.all
                      if len(s.data.columns) == ncols]
        for part in self.partitions.values():
            if part.schema in candidates or not candidates:
                return part.schema
        if candidates:
            return candidates[0]
        return self.schemas.all[0]

    # ------------------------------------------------------------ query path

    def scan_batch(self, part_ids: Sequence[int], start_time: int,
                   end_time: int, column_id: Optional[int] = None):
        got = self._scan_batch_bulk(part_ids, start_time, end_time,
                                    column_id)
        if got is not None:
            return got
        parts = self._resolve_partitions(part_ids, start_time, end_time)
        # pin resolved partitions for the duration of the scan: later
        # page-ins must not LRU-evict earlier ones out of this query
        self._pinned.parts = parts
        try:
            self._predecode_chunks(parts.values(), start_time, end_time)
            return super().scan_batch(part_ids, start_time, end_time,
                                      column_id)
        finally:
            self._pinned.parts = None

    def _scan_batch_bulk(self, part_ids: Sequence[int], start_time: int,
                         end_time: int, column_id: Optional[int]):
        """Fully-vectorized scan for the pure paged/cold case: every
        requested partition is read-only (paged or on disk), one schema,
        numeric value column.  Page-in is bulk (<_page_in_bulk>), and
        the padded [S, R] batch assembles with whole-array ops instead
        of a per-partition read_range + row-copy loop — the per-series
        Python constants were the whole cold-scan budget (VERDICT r4
        missing #4).  Returns (tags, batch) or None to fall back."""
        from filodb_tpu import native
        if native.batch_decoder() is None:
            return None
        self._check_integrity()
        live = self.partitions
        paged = self.paged.snapshot()
        parts: dict[int, TimeSeriesPartition] = {}
        missing: list[int] = []
        ids: list[int] = []
        for p in part_ids:
            pid = int(p)
            ids.append(pid)
            if pid in live:
                return None   # live series mix in: generic path handles
            part = paged.get(pid)
            if part is None:
                missing.append(pid)
            else:
                parts[pid] = part
        # scanned-bytes cap: resident paged chunks are costed in Python
        # (precomputed raw_nbytes); the page-in read streams the rest of
        # the budget instead of paying a LENGTH() metadata pre-pass
        cap = self.config.max_data_per_shard_query
        resident_bytes = sum(
            cs.nbytes for part in parts.values() for cs in part.chunks
            if cs.info.end_time >= start_time
            and cs.info.start_time <= end_time)
        if resident_bytes > cap:
            raise QueryLimitExceeded(
                f"query would scan over {resident_bytes} bytes on shard "
                f"{self.shard_num}, cap is {cap} "
                "(max-data-per-shard-query)")
        if missing:
            # no pre-paged survivors -> missing covers every id, so the
            # page-in may fuse the padded batch assembly into its
            # decode pass and serve the query directly
            fuse = None if parts else (ids, start_time, end_time,
                                       column_id)
            with _pagein_timed(self, "bulk"):
                try:
                    got = self._page_in_bulk(
                        missing, byte_cap=cap - resident_bytes, fuse=fuse)
                except ScanBytesExceeded:
                    # full-history bytes crossed the budget; only chunks
                    # overlapping the range count, so do the precise
                    # metadata check (raises when genuinely over), then
                    # retry uncapped — falling back to the generic path
                    # would read the same multi-MB row set a third time
                    self._cap_data_scanned(parts.values(), missing,
                                           start_time, end_time)
                    got = self._page_in_bulk(missing, fuse=fuse)
            if got is None:
                return None
            built, ftags, fbatch = got
            if ftags is not None:
                return ftags, fbatch
            parts.update(built)
        order = [pid for pid in ids if pid in parts]
        if not order:
            return [], None
        schema = parts[order[0]].schema
        cid = schema.data.value_column_id if column_id is None \
            else column_id
        if cid < 1 or cid >= len(schema.data.columns) \
                or schema.data.columns[cid].ctype not in _NUMERIC:
            return None
        # paged partitions built by the bulk path arrive pre-decoded;
        # ones paged by the generic path may not be — fill in one call
        self._predecode_chunks(parts.values(), start_time, end_time)
        col_idx = cid - 1
        h0 = schema.schema_hash
        ts_parts, val_parts = [], []
        counts = np.zeros(len(order), dtype=np.int64)
        lo_info, hi_info = _MAX_TIME, -_MAX_TIME
        q = integrity.QUARANTINE
        for i, pid in enumerate(order):
            part = parts[pid]
            if part.schema.schema_hash != h0:
                return None
            q_ids = q.chunk_ids(part.partkey) if q else ()
            c = 0
            for cs in part.chunks:
                info = cs.info
                if info.end_time < start_time \
                        or info.start_time > end_time:
                    continue
                if q_ids and info.chunk_id in q_ids:
                    continue   # quarantined: serve partial, never corrupt
                got = part._decoded.get(info.chunk_id)
                if got is None:
                    return None   # mixed schema within partition etc.
                if info.start_time < lo_info:
                    lo_info = info.start_time
                if info.end_time > hi_info:
                    hi_info = info.end_time
                ts_parts.append(got[0])
                val_parts.append(got[1][col_idx])
                c += len(got[0])
            counts[i] = c
        total = int(counts.sum())
        if total == 0:
            flat_ts = _EMPTY_I64
            flat_val = np.empty(0, dtype=np.float64)
        else:
            flat_ts = np.concatenate(ts_parts)
            flat_val = np.concatenate(val_parts).astype(np.float64,
                                                        copy=False)
            # trim to [start, end] globally: timestamps are sorted
            # within each partition, so a flat mask + per-partition
            # prefix-sum recount preserves per-series order (chunk-info
            # bounds decide whether any trim is needed at all)
            if lo_info < start_time or hi_info > end_time:
                offs = np.zeros(len(order) + 1, dtype=np.int64)
                np.cumsum(counts, out=offs[1:])
                mask = (flat_ts >= start_time) & (flat_ts <= end_time)
                cm = np.zeros(total + 1, dtype=np.int64)
                np.cumsum(mask, out=cm[1:])
                counts = cm[offs[1:]] - cm[offs[:-1]]
                flat_ts = flat_ts[mask]
                flat_val = flat_val[mask]
        # padded [S, R] assembly, same geometry as build_batch
        S = len(order)
        R = pad_rows(int(counts.max()) if S else 0,
                     self.config.batch_row_pad)
        S_pad = max(S, _round_up(S, self.config.batch_series_pad))
        cnts = counts_pad(counts.astype(np.int32), S_pad)
        ts2d = np.empty((S_pad, R), dtype=np.int64)
        val2d = np.empty((S_pad, R), dtype=np.float64)
        if fill_batch_pads(ts2d, val2d, cnts, S):
            # uniform row count (the whole-dashboard page-in): one
            # reshaped block copy instead of a mask scatter
            r0 = int(counts[0]) if S else 0
            ts2d[:S, :r0] = flat_ts.reshape(S, r0)
            val2d[:S, :r0] = flat_val.reshape(S, r0)
        elif flat_ts.size:
            rowmask = np.arange(R)[None, :] < cnts[:, None]
            ts2d[rowmask] = flat_ts
            val2d[rowmask] = flat_val
        self.paged.touch_many(order)
        tags = [parts[pid].tags for pid in order]
        return tags, ChunkBatch(ts2d, val2d, cnts)

    @staticmethod
    def _predecode_chunks(parts, start_time: int, end_time: int) -> None:
        """Batch-decode every undecoded chunk the scan will touch with
        ONE native call, filling each partition's decoded-chunk cache so
        read_range becomes pure concatenation (reference:
        DemandPagedChunkStore.scala:34 pages straight into block memory;
        VERDICT r4 missing #4 — the cold ODP path paid a per-chunk
        Python decode per partition).  Quarantined chunks are excluded;
        a corrupt chunk discovered here is diagnosed per chunk,
        quarantined, and the rest still decode."""
        from filodb_tpu.core.chunk import decode_partitions_batch
        groups, owners = [], []
        schema = None
        q = integrity.QUARANTINE
        for part in parts:
            if schema is None:
                schema = part.schema
            elif part.schema.schema_hash != schema.schema_hash:
                return                     # mixed schemas: per-chunk path
            decoded = part._decoded
            q_ids = q.chunk_ids(part.partkey) if q else ()
            for cs in part.chunks:
                if cs.info.end_time < start_time \
                        or cs.info.start_time > end_time \
                        or cs.info.chunk_id in decoded:
                    continue
                if q_ids and cs.info.chunk_id in q_ids:
                    continue
                groups.append([cs])
                owners.append((part, cs.info.chunk_id))
        if not groups or schema is None:
            return
        t0 = time.perf_counter()
        try:
            try:
                decoded_all = decode_partitions_batch(schema, groups)
            except (ValueError, IndexError, struct.error):
                # ONE corrupt chunk fails the whole batch decode: redo per
                # chunk so the culprit gets its structured diagnosis +
                # quarantine while every healthy chunk still fills its cache
                for (part, _cid), (cs,) in zip(owners, groups):
                    try:
                        part._decoded_chunk(cs)
                    except integrity.CorruptVectorError as err:
                        part._note_corrupt(err)
                return
            for (part, cid), decoded in zip(owners, decoded_all):
                part._decoded[cid] = decoded
        finally:
            ctx = _active_ctx()
            if ctx is not None:
                ctx.note_timing("decode", time.perf_counter() - t0)

    def _cap_data_scanned(self, resident_parts, missing_ids: Sequence[int],
                          start_time: int, end_time: int) -> None:
        """Only chunks overlapping the query range count against the cap —
        a narrow query over a long-retention series must not be rejected
        for history it will never decode.  Absent partitions are costed
        from store metadata before their vectors are read."""
        total = sum(c.nbytes
                    for p in resident_parts for c in p.chunks
                    if c.info.end_time >= start_time
                    and c.info.start_time <= end_time)
        cap = self.config.max_data_per_shard_query
        if missing_ids and total <= cap:
            pks = []
            for pid in missing_ids:
                try:
                    pks.append(self.index.partkey(pid))
                except KeyError:
                    continue
            if pks:
                total += self.store.scan_bytes(self.dataset, self.shard_num,
                                               pks, start_time, end_time)
        if total > cap:
            raise QueryLimitExceeded(
                f"query would scan {total} bytes on shard {self.shard_num}, "
                f"cap is {cap} (max-data-per-shard-query)")

    def lookup_partitions(self, filters: Sequence[ColumnFilter],
                          start_time: int, end_time: int,
                          limit: Optional[int] = None) -> PartLookupResult:
        """Unlike the in-memory-only base (which reports non-resident ids as
        ``missing_partkeys``), every indexed id is servable here — absent
        partitions page in at scan time."""
        self._check_integrity()
        ids = self.index.part_ids_from_filters(filters, start_time, end_time,
                                               limit)
        first_schema = None
        out: list[int] = []
        hash_of = self.part_schema_hash.get
        parts_get = self.partitions.get
        paged_get = self.paged.get
        for i in ids:
            pid = int(i)
            # create/recover-time hash first: the common all-indexed case
            # then needs no per-id paged-LRU lock round-trip
            h = hash_of(pid)
            if h is None:
                part = parts_get(pid) or paged_get(pid)
                if part is not None:
                    h = part.schema.schema_hash
            if h is not None:
                if first_schema is None:
                    first_schema = h
                if h != first_schema:
                    continue  # one schema per lookup, like the base class
            out.append(pid)
        return PartLookupResult(self.shard_num,
                                np.asarray(out, dtype=np.int32), [],
                                first_schema)

    # -------------------------------------------------------------- eviction

    def evict_partitions(self, n: int) -> int:
        """Unlike the base (in-memory-only) shard, keep index + part-set
        entries so queries can page evicted series back from disk
        (reference: Lucene entries survive eviction; evicted partkeys
        tracked in a bloom filter, TimeSeriesShard.scala:1308-1401)."""
        # stopped-longest-ago first; ghost ids (already evicted, still
        # indexed) must not consume the quota
        stopped = [pid for pid in
                   self.index.part_ids_ordered_by_end_time(
                       n + max(len(self.index_only_ids()), 0))
                   if pid in self.partitions]
        victims = stopped[:n]
        if len(victims) < n:
            # not enough stopped series: fall back to least-recently-written
            # active partitions (they are safely pageable once flushed)
            seen = set(victims)
            active = sorted((p.latest_timestamp, pid)
                            for pid, p in self.partitions.items()
                            if pid not in seen)
            victims += [pid for _, pid in active[:n - len(victims)]]
        evicted = 0
        itime = int(time.time() * 1000)
        for pid in victims:
            part = self.partitions.get(pid)
            if part is None:
                continue
            # persist anything not yet flushed — eviction must not lose data,
            # must stay visible to ingestion-time scans (batch downsampler),
            # and must still feed the streaming downsampler
            pending = part.make_flush_chunks()
            if pending:
                self.store.write_chunks(self.dataset, self.shard_num, pending,
                                        ingestion_time=itime)
                self.store.write_part_keys(
                    self.dataset, self.shard_num,
                    [PartKeyRecord(part.partkey, self.index.start_time(pid),
                                   self.index.end_time(pid), self.shard_num,
                                   part.schema.schema_hash)])
                if self.downsample_publisher is not None:
                    self._downsampler_for(
                        part.schema.schema_hash).downsample_chunksets(
                        [(part.tags, cs) for cs in pending])
            # under _odp_lock so an in-flight backfill compute for this pid
            # finishes (and its live-partition identity check then fails)
            # before the stale entries are dropped
            with self._odp_lock:
                del self.partitions[pid]
                self.bump_removal_epoch()    # invalidates grid prep caches
                self.paged.pop(pid)          # cached copy lacks the tail
                self.paged.pop(("bf", pid))  # list is live-part relative
                from filodb_tpu.utils.devicewatch import LEDGER
                LEDGER.note_eviction(self._ledger_owner, "epoch_purge",
                                     n=2)
                # hard reclaim invariant (still under _odp_lock, so no
                # legitimate re-page-in can land): a popped entry that is
                # STILL cached means a publish resurrected stale buffers
                # past the gen guard — fail the shard, don't serve it
                for key in (pid, ("bf", pid)):
                    if self.paged.get(key) is not None:
                        self._fail_integrity(
                            f"evicted entry {key!r} resurrected in the "
                            f"page cache during eviction")
            self.evicted_keys.add(part.partkey)
            self.stats.partitions_evicted += 1
            evicted += 1
        if evicted:
            # full byte-accounting audit once per eviction batch (O(cache
            # entries), off the query path)
            try:
                self.paged.check_invariants()
            except IntegrityInvariantError as e:
                self._fail_integrity(str(e))
        return evicted

    @staticmethod
    def _count_verified(n: int, crcs) -> None:
        """Bulk decode succeeded with deferred checksum verification:
        credit the verified-chunks counter (the store skipped its pass)."""
        if crcs is not None and n:
            from filodb_tpu.utils.observability import integrity_metrics
            integrity_metrics()["chunks_verified"].inc(n)

    def _fail_integrity(self, detail: str) -> None:
        """Record the broken invariant, count it, and fail the shard:
        every subsequent scan raises instead of serving stale buffers."""
        self.integrity_failed = detail
        integrity.note_invariant_failure(self.dataset, self.shard_num,
                                         detail)
        raise IntegrityInvariantError(
            f"shard {self.shard_num} failed integrity: {detail}")

    def index_only_ids(self) -> list[int]:
        """Ids present in the index but not resident in memory."""
        return [pid for pid in self.part_set.values()
                if pid not in self.partitions]
