"""Ingest watermark ledger: how far behind is each shard, exactly.

The reference's first operational question — "is ingestion keeping up"
— is answered by per-shard offsets and per-group recovery watermarks
(reference: TimeSeriesShard group watermarks :155-157, checkpoint reads
IngestionActor.scala:193-217, ShardHealthStats).  All of those already
exist here (broker ``end_offset``, ``shard.latest_offset``,
``shard.group_watermarks``, persisted checkpoints) but were dark.  The
:class:`WatermarkLedger` samples them into one monotone chain per
shard::

    broker_end >= ingested >= flushed(group min) >= checkpoint

exported as ``filodb_ingest_watermark_offset{stage=}`` plus lag gauges
in rows AND seconds, joined with the FlushScheduler's queue depth/age
and the ShardMapper's status/recovery progress into the
``/admin/shards`` health tree.  A shard whose row lag is nonzero while
its ingested offset makes no progress for ``stall_window_s`` raises an
``ingest.stall`` flight-recorder event + ``filodb_ingest_stalls_total``
once per episode (re-armed on progress) — the alertable form of "the
consumer wedged".

One ledger per server (NOT process-wide): in-process multi-node tests
run several nodes whose (dataset, shard) keys collide; the ``node``
label keeps their gauge rows apart.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from filodb_tpu.utils.observability import PeriodicThread

_METRICS = None

_STAGES = ("broker_end", "ingested", "flushed", "checkpoint")


def _m() -> dict:
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import watermark_metrics
        _METRICS = watermark_metrics()
    return _METRICS


class _Watch:
    __slots__ = ("memstore", "mapper", "end_offset_fn")

    def __init__(self, memstore, mapper, end_offset_fn):
        self.memstore = memstore
        self.mapper = mapper
        self.end_offset_fn = end_offset_fn


class WatermarkLedger:
    """Samples every watched dataset's shards into the health tree.

    ``sample()`` is driven by the standalone sampler thread AND by each
    ``/admin/shards`` request, so the endpoint always shows live
    numbers; stall detection state advances on every call."""

    def __init__(self, stall_window_s: float = 30.0, node: str = ""):
        self.stall_window_s = float(stall_window_s)
        self.node = node
        self._watches: dict[str, _Watch] = {}  # guarded-by: _lock
        # (dataset, shard) -> stall state; the stall machine advances
        # under the ledger lock or concurrent sampler + /admin/shards
        # passes double-count an episode boundary (PR 11 review fix,
        # now lint-enforced)
        self._stall: dict[tuple, dict] = {}  # guarded-by: _lock
        # (dataset, shard) label sets this ledger has exported gauge
        # rows for — close() removes them (the PR 11 stale-row lesson:
        # a dead server's `stalled=1` row would otherwise sit in the
        # process registry forever, and the self-monitoring rule pack
        # ALERTS on that gauge)
        self._emitted: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()

    def watch(self, dataset: str, memstore, mapper=None,
              end_offset_fn: Optional[Callable[[int], int]] = None) -> None:
        """Track a dataset: shards are enumerated FRESH on every sample
        (dynamic shard starts/stops need no re-registration).
        ``end_offset_fn(shard)`` returns the broker head for that
        shard's partition; None = no broker stage (in-proc sources)."""
        with self._lock:
            self._watches[dataset] = _Watch(memstore, mapper, end_offset_fn)

    def unwatch(self, dataset: str) -> None:
        with self._lock:
            self._watches.pop(dataset, None)
            gone = [k for k in self._emitted if k[0] == dataset]
            for k in gone:
                self._emitted.discard(k)
        for _ds, shard in gone:
            self._remove_rows(dataset, shard)

    def _remove_rows(self, dataset: str, shard: int) -> None:
        m = _m()
        labels = {"dataset": dataset, "shard": shard, "node": self.node}
        for stage in _STAGES:
            m["offset"].remove(stage=stage, **labels)
        m["lag_rows"].remove(**labels)
        m["lag_seconds"].remove(**labels)
        m["stalled"].remove(**labels)

    def close(self) -> None:
        """Drop every gauge row this ledger exported.  A shut-down
        node's per-shard rows — especially a lingering ``stalled=1`` —
        must not keep feeding scrapes (and the alerting rules watching
        them) forever."""
        with self._lock:
            emitted, self._emitted = self._emitted, set()
            self._watches.clear()
        for dataset, shard in emitted:
            self._remove_rows(dataset, shard)

    def watching(self) -> list[str]:
        """Datasets currently tracked (the HTTP layer syncs late-bound
        datasets into its lazy default ledger without clobbering
        configured watches)."""
        with self._lock:
            return list(self._watches)

    # --------------------------------------------------------------- sample

    def _flush_row(self, sh) -> Optional[dict]:
        sched = getattr(sh, "flush_scheduler", None)
        if sched is None:
            return None
        try:
            return sched.snapshot()
        except Exception:  # noqa: BLE001 — scheduler mid-close
            return None

    def _checkpoint(self, dataset: str, sh) -> Optional[int]:
        try:
            cps = sh.meta.read_checkpoints(dataset, sh.shard_num)
        except Exception:  # noqa: BLE001 — meta store shut down
            return None
        return min(cps.values()) if cps else -1

    def _note_stall(self, dataset: str, shard: int, ingested: int,
                    lag_rows: int, now: float) -> bool:
        """Advance the per-shard stall machine; returns True while the
        shard counts as stalled.  One counter bump + flight event per
        episode — progress re-arms it.  The whole step runs under the
        ledger lock: the background sampler and inline /admin/shards
        requests sample concurrently, and an unsynchronized fired-check
        would double-count the episode boundary."""
        key = (dataset, shard)
        with self._lock:
            st = self._stall.get(key)
            if lag_rows <= 0:
                self._stall.pop(key, None)
                return False
            if st is None or st["offset"] != ingested:
                self._stall[key] = {"offset": ingested, "since": now,
                                    "fired": False}
                return False
            if now - st["since"] < self.stall_window_s:
                return False
            fire = not st["fired"]
            st["fired"] = True
            since = st["since"]
        if fire:
            _m()["stalls"].inc(dataset=dataset, shard=shard, node=self.node)
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("ingest.stall", dataset=dataset, shard=shard,
                          node=self.node, lag_rows=lag_rows,
                          stalled_for_s=round(now - since, 3))
        return True

    def _shard_row(self, dataset: str, sh, watch: _Watch,
                   now_mono: float, now_ms: int) -> dict:
        m = _m()
        labels = {"dataset": dataset, "shard": sh.shard_num,
                  "node": self.node}
        ingested = sh.latest_offset
        flushed = min(sh.group_watermarks) if sh.group_watermarks else -1
        checkpoint = self._checkpoint(dataset, sh)
        broker_end = None
        if watch.end_offset_fn is not None:
            try:
                broker_end = int(watch.end_offset_fn(sh.shard_num))
            except Exception:  # noqa: BLE001 — broker unreachable
                broker_end = None
        # end_offset is the NEXT offset to be written; latest_offset the
        # last one ingested — lag is whatever sits between them
        lag_rows = max(0, broker_end - 1 - ingested) \
            if broker_end is not None else 0
        lag_seconds = 0.0
        if lag_rows > 0 and sh.latest_ingest_ts >= 0:
            lag_seconds = max(0.0, (now_ms - sh.latest_ingest_ts) / 1000.0)
        stalled = self._note_stall(dataset, sh.shard_num, ingested,
                                   lag_rows, now_mono)
        watermarks = {"ingested": ingested, "flushed": flushed,
                      "groups": list(sh.group_watermarks)}
        if broker_end is not None:
            watermarks["broker_end"] = broker_end
        if checkpoint is not None:
            watermarks["checkpoint"] = checkpoint
        for stage in _STAGES:
            if stage in watermarks:
                m["offset"].set(watermarks[stage], stage=stage, **labels)
        with self._lock:
            self._emitted.add((dataset, sh.shard_num))
        m["lag_rows"].set(lag_rows, **labels)
        m["lag_seconds"].set(lag_seconds, **labels)
        # level-based stall flag (ISSUE 9): the stalls_total counter's
        # label set is BORN at 1 (created by the first episode), so
        # increase() over a scrape of it can never see the 0->1 edge —
        # alerting rules need this 0/1 gauge, which exists from the
        # first sample and clears when ingest resumes
        m["stalled"].set(1.0 if stalled else 0.0, **labels)
        row = {"shard": sh.shard_num,
               "watermarks": watermarks,
               "lag": {"rows": lag_rows, "seconds": round(lag_seconds, 3)},
               "stalled": stalled,
               "rows_ingested": sh.stats.rows_ingested,
               "latest_ingest_ts": sh.latest_ingest_ts}
        flush = self._flush_row(sh)
        if flush is not None:
            row["flush"] = flush
        if watch.mapper is not None and \
                sh.shard_num < watch.mapper.total_shards:
            topo = watch.mapper.topology
            if topo.split_phase is not None:
                # live split (ISSUE 13): label each row's role so the
                # health tree shows catch-up/cutover progress in place
                parent = watch.mapper.split_parent_of(sh.shard_num)
                row["split"] = {
                    "phase": topo.split_phase,
                    "role": "child" if parent is not None else "parent",
                    **({"parent": parent} if parent is not None else
                       {"child": sh.shard_num + (topo.split_base or 0)}),
                    "rows_filtered": sh.stats.rows_split_filtered,
                }
            st = watch.mapper.state(sh.shard_num)
            # the SERVING view, matching what query routing does: a
            # shard with any queryable replica reports that (best)
            # status — a dead primary must not show a served shard as
            # down (the per-replica rows below carry each copy's truth)
            serving = st.serving_replica()
            best = st.best_status
            row["status"] = best.value
            row["queryable"] = best.queryable
            row["owner"] = serving.node if serving is not None else st.node
            row["recovery_progress"] = serving.recovery_progress \
                if serving is not None else st.recovery_progress
            if st.replicas:
                # per-replica divergence view (ISSUE 7): each copy's
                # node, status, and watermark lag behind the group head
                # — a lagging replica is visibly behind, never silently
                # wrong
                head = watch.mapper.group_head(sh.shard_num)
                row["replicas"] = [
                    {"node": r.node, "status": r.status.value,
                     "recovery_progress": r.recovery_progress,
                     "watermark": r.watermark,
                     "lag_rows": max(head - r.watermark, 0)
                     if head >= 0 and r.watermark >= 0 else None}
                    for r in st.replicas]
        return row

    def sample(self) -> dict:
        """One pass over every watched dataset: refresh the gauges,
        advance stall detection, return the /admin/shards tree."""
        from filodb_tpu.memstore.cardinality import sample_tenant_gauges
        with self._lock:
            watches = dict(self._watches)
        now_mono = time.monotonic()
        now_ms = int(time.time() * 1000)
        datasets: dict = {}
        for ds, watch in watches.items():
            shards = watch.memstore.shards(ds)
            rows = [self._shard_row(ds, sh, watch, now_mono, now_ms)
                    for sh in shards]
            rows.sort(key=lambda r: r["shard"])
            # the tenant cardinality gauges ride the sampling cadence
            tenant_label = next(
                (sh.series_quota.tenant_label for sh in shards
                 if getattr(sh, "series_quota", None) is not None),
                "_ns_")
            try:
                sample_tenant_gauges(ds, shards, tenant_label)
            except Exception:  # noqa: BLE001 — sampling never breaks serving
                pass
            datasets[ds] = {
                "shards": rows,
                "totals": {
                    "lag_rows": sum(r["lag"]["rows"] for r in rows),
                    "stalled": sum(1 for r in rows if r["stalled"]),
                    "queryable": sum(1 for r in rows
                                     if r.get("queryable", True)),
                },
            }
            if watch.mapper is not None:
                datasets[ds]["topology"] = \
                    watch.mapper.topology.as_payload()
        return {"node": self.node, "stall_window_s": self.stall_window_s,
                "sampled_at_ms": now_ms, "datasets": datasets}


class TierWatermarks:
    """Cluster-wide rollup tier closure watermarks (ROADMAP 2b).

    Each node's rollup engine knows the closure boundary only for the
    shards IT rolls; a multi-node coordinator that stitches raw/rolled
    at its LOCAL engine's boundary is needlessly conservative for
    shards other nodes roll.  Owners publish their per-dataset/tier
    ``rolled_through`` in the ``/__health`` payload, the StatusPoller
    feeds peers' values in here, and the resolution router stitches at
    :meth:`cluster_rolled_through` — the min across the dataset's
    shard-owning nodes, i.e. the newest stamp every owner has closed.

    Per-server (not process-wide), like the WatermarkLedger: in-process
    multi-node tests would otherwise cross-feed each other's rows.
    """

    def __init__(self, node: str = ""):
        self.node = node
        # (peer node, dataset) -> {resolution_ms: rolled_through_ms}
        self._peers: dict[tuple, dict] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def note(self, peer: str, dataset: str, tiers: dict) -> None:
        """Fold one peer's gossiped ``{resolution_ms: through_ms}``;
        values only ever advance (closure is monotone — a stale poll
        racing a fresh one must not drag the boundary back)."""
        with self._lock:
            row = self._peers.setdefault((peer, dataset), {})
            for res, through in tiers.items():
                res = int(res)
                row[res] = max(row.get(res, -(1 << 62)), int(through))

    def peer_value(self, peer: str, dataset: str,
                   res: int) -> Optional[int]:
        with self._lock:
            row = self._peers.get((peer, dataset))
            return None if row is None else row.get(int(res))

    def forget(self, peer: str) -> None:
        """Drop a departed node's rows: a dead owner's frozen boundary
        must not cap the cluster stitch forever (its shards reassign
        and the new owner republishes)."""
        with self._lock:
            for key in [k for k in self._peers if k[0] == peer]:
                del self._peers[key]

    def cluster_min(self, dataset: str, res: int,
                    peers) -> Optional[int]:
        """Min of the given peers' gossiped closure watermarks — the
        peer half of the cluster-wide stitch boundary.  ``None`` when
        any peer has not gossiped yet (the caller falls back to the
        local engine's conservative boundary, never to a guess)."""
        vals = []
        for peer in set(peers):
            v = self.peer_value(peer, dataset, res)
            if v is None:
                return None
            vals.append(v)
        return min(vals) if vals else None

    def snapshot(self) -> dict:
        with self._lock:
            return {f"{peer}/{ds}": {str(r): v for r, v in row.items()}
                    for (peer, ds), row in sorted(self._peers.items())}


class WatermarkSampler(PeriodicThread):
    """Background driver: ``ledger.sample()`` every ``interval_s`` so
    lag gauges and stall events exist without anyone polling
    /admin/shards (the alertable path)."""

    def __init__(self, ledger: WatermarkLedger, interval_s: float = 10.0):
        super().__init__(ledger.sample, interval_s, "watermark-sampler")
        self.ledger = ledger
